! Fortran bindings for the slate_tpu C API (include/slate_tpu.h).
!
! Reference analogue: tools/fortran generates iso_c_binding wrappers over the
! C API; this module is the hand-written equivalent for the TPU build.
!
!   use slate_tpu
!   info = slate_dgesv(n, nrhs, A, lda, ipiv, B, ldb)
!
! Link with -lslate_c_api (which embeds the Python runtime).

module slate_tpu
  use iso_c_binding
  implicit none

  interface
     integer(c_int) function slate_init() bind(c, name="slate_init")
       import :: c_int
     end function slate_init

     subroutine slate_finalize() bind(c, name="slate_finalize")
     end subroutine slate_finalize

     integer(c_int) function slate_gridinit(p, q) bind(c, name="slate_gridinit")
       import :: c_int
       integer(c_int), value :: p, q
     end function slate_gridinit

     subroutine slate_gridexit() bind(c, name="slate_gridexit")
     end subroutine slate_gridexit

     integer(c_int) function slate_dgemm(transa, transb, m, n, k, alpha, &
          A, lda, B, ldb, beta, C, ldc) bind(c, name="slate_dgemm")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: transa, transb
       integer(c_int64_t), value :: m, n, k, lda, ldb, ldc
       real(c_double), value :: alpha, beta
       real(c_double), intent(in) :: A(*), B(*)
       real(c_double), intent(inout) :: C(*)
     end function slate_dgemm

     integer(c_int) function slate_dgesv(n, nrhs, A, lda, ipiv, B, ldb) &
          bind(c, name="slate_dgesv")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: n, nrhs, lda, ldb
       real(c_double), intent(inout) :: A(*), B(*)
       integer(c_int64_t), intent(out) :: ipiv(*)
     end function slate_dgesv

     integer(c_int) function slate_dposv(uplo, n, nrhs, A, lda, B, ldb) &
          bind(c, name="slate_dposv")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: uplo
       integer(c_int64_t), value :: n, nrhs, lda, ldb
       real(c_double), intent(inout) :: A(*), B(*)
     end function slate_dposv

     integer(c_int) function slate_dpotrf(uplo, n, A, lda) &
          bind(c, name="slate_dpotrf")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: uplo
       integer(c_int64_t), value :: n, lda
       real(c_double), intent(inout) :: A(*)
     end function slate_dpotrf

     integer(c_int) function slate_dgels(trans, m, n, nrhs, A, lda, B, ldb) &
          bind(c, name="slate_dgels")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: trans
       integer(c_int64_t), value :: m, n, nrhs, lda, ldb
       real(c_double), intent(inout) :: A(*), B(*)
     end function slate_dgels

     integer(c_int) function slate_dsyev(jobz, uplo, n, A, lda, W) &
          bind(c, name="slate_dsyev")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: jobz, uplo
       integer(c_int64_t), value :: n, lda
       real(c_double), intent(inout) :: A(*)
       real(c_double), intent(out) :: W(*)
     end function slate_dsyev

     real(c_double) function slate_dlange(norm, m, n, A, lda) &
          bind(c, name="slate_dlange")
       import :: c_int64_t, c_double, c_char
       character(kind=c_char), value :: norm
       integer(c_int64_t), value :: m, n, lda
       real(c_double), intent(in) :: A(*)
     end function slate_dlange

     integer(c_int) function slate_dgetrf(m, n, A, lda, ipiv) &
          bind(c, name="slate_dgetrf")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: m, n, lda
       real(c_double), intent(inout) :: A(*)
       integer(c_int64_t), intent(out) :: ipiv(*)
     end function slate_dgetrf

     integer(c_int) function slate_dgetrs(trans, n, nrhs, A, lda, ipiv, &
          B, ldb) bind(c, name="slate_dgetrs")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: trans
       integer(c_int64_t), value :: n, nrhs, lda, ldb
       real(c_double), intent(in) :: A(*)
       integer(c_int64_t), intent(in) :: ipiv(*)
       real(c_double), intent(inout) :: B(*)
     end function slate_dgetrs

     integer(c_int) function slate_dtrsm(side, uplo, transa, diag, m, n, &
          alpha, A, lda, B, ldb) bind(c, name="slate_dtrsm")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: side, uplo, transa, diag
       integer(c_int64_t), value :: m, n, lda, ldb
       real(c_double), value :: alpha
       real(c_double), intent(in) :: A(*)
       real(c_double), intent(inout) :: B(*)
     end function slate_dtrsm

     integer(c_int) function slate_dsygv(itype, jobz, uplo, n, A, lda, &
          B, ldb, W) bind(c, name="slate_dsygv")
       import :: c_int, c_int64_t, c_double, c_char
       integer(c_int64_t), value :: itype
       character(kind=c_char), value :: jobz, uplo
       integer(c_int64_t), value :: n, lda, ldb
       real(c_double), intent(inout) :: A(*), B(*)
       real(c_double), intent(out) :: W(*)
     end function slate_dsygv

     integer(c_int) function slate_dgesvd(jobu, jobvt, m, n, A, lda, S, &
          U, ldu, VT, ldvt) bind(c, name="slate_dgesvd")
       import :: c_int, c_int64_t, c_double, c_char
       character(kind=c_char), value :: jobu, jobvt
       integer(c_int64_t), value :: m, n, lda, ldu, ldvt
       real(c_double), intent(inout) :: A(*)
       real(c_double), intent(out) :: S(*), U(*), VT(*)
     end function slate_dgesvd
  end interface

end module slate_tpu
