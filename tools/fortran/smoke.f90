! Fortran smoke test over the slate_tpu C API (compiled + run in CI with
! gfortran; the local image carries no Fortran compiler, so
! tests/test_fortran.py skips unless one is present).
!
!   gfortran tools/fortran/slate_tpu.f90 tools/fortran/smoke.f90 \
!     -I. -L native -lslate_c_api -Wl,-rpath,native -o smoke && ./smoke
program smoke
  use slate_tpu
  use iso_c_binding
  implicit none
  integer(c_int64_t), parameter :: n = 12, nrhs = 2
  real(c_double) :: A(n, n), Asave(n, n), B(n, nrhs), Bsave(n, nrhs)
  real(c_double) :: W(n), resid
  integer(c_int64_t) :: ipiv(n)
  integer(c_int) :: info
  integer :: i, j, k
  integer :: nfail

  nfail = 0
  call random_number(A)
  A = A - 0.5d0
  do i = 1, int(n)
     A(i, i) = A(i, i) + real(n, c_double)
  end do
  Asave = A
  call random_number(B)
  Bsave = B

  ! getrf + getrs
  info = slate_dgetrf(n, n, A, n, ipiv)
  if (info /= 0) nfail = nfail + 1
  info = slate_dgetrs('n', n, nrhs, A, n, ipiv, B, n)
  if (info /= 0) nfail = nfail + 1
  resid = 0.0d0
  do j = 1, int(nrhs)
     do i = 1, int(n)
        resid = max(resid, abs(sum(Asave(i, :) * B(:, j)) - Bsave(i, j)))
     end do
  end do
  print '(a, es10.3)', 'fortran getrf+s resid ', resid
  if (resid > 1.0d-10) nfail = nfail + 1

  ! syev values of the symmetrized matrix
  A = 0.5d0 * (Asave + transpose(Asave))
  info = slate_dsyev('n', 'l', n, A, n, W)
  if (info /= 0) nfail = nfail + 1
  do k = 2, int(n)
     if (W(k) < W(k - 1)) nfail = nfail + 1   ! ascending contract
  end do
  print '(a, i0)', 'fortran nfail = ', nfail
  if (nfail == 0) then
     print '(a)', 'FORTRAN PASS'
  else
     print '(a)', 'FORTRAN FAIL'
     stop 1
  end if
end program smoke
