#!/usr/bin/env python
"""Generate SCALING.md + pinned collective-volume envelopes for CI.

The per-routine scaling artifact ROADMAP item 4 asks for: every distributed
routine in ``slate_tpu/parallel`` compiled on CPU meshes at P ∈ {2, 4, 8}
(compile-only — the same in-env discipline as tools/twostage_scale.py), with
compiled collective volume, per-device flops/bytes, and the comm/compute
ratio per row.  The P=2 collective columns are pinned into SCALING_PINS.json
so a communication-volume regression fails CI (tests/test_perf_pins.py and
the ci.yml ``scaling-audit`` step) before a capture window is spent.

Usage::

    python tools/gen_scaling.py                  # full table -> SCALING.md
    python tools/gen_scaling.py --update-pins    # also refresh SCALING_PINS.json
    python tools/gen_scaling.py --check          # P=2 only, diff vs pins, rc!=0
                                                 # on regression (the CI gate)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from force_cpu import force_cpu_backend

force_cpu_backend(virtual_devices=8)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MD_PATH = os.path.join(REPO, "SCALING.md")
PINS_PATH = os.path.join(REPO, "SCALING_PINS.json")

PINS_SCHEMA = "slate_tpu.scaling_pins/v1"
#: regression envelope: measured collective bytes may grow to this factor of
#: the pinned value before the gate trips (compiler-version jitter is a few
#: percent; a schedule regression of the round-5 CALU kind is 2-3x)
BYTES_SLACK = 1.25
#: extra collective *sites* tolerated over the pin (fusion jitter)
COUNT_SLACK = 2


def _fmt_bytes(b) -> str:
    if b is None:
        return "-"
    if b >= 1 << 20:
        return f"{b / (1 << 20):.2f} MiB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f} KiB"
    return f"{b} B"


def _fmt_ratio(r) -> str:
    return f"{r:.2e}" if r is not None else "-"


def _collectives_cell(row) -> str:
    ops = row.get("collectives") or {}
    if not ops:
        return "-"
    return ", ".join(f"{op}×{e['count']}" for op, e in sorted(ops.items()))


def render_markdown(rows, pset) -> str:
    from slate_tpu.obs.scaling import AUDIT_KD, AUDIT_N, AUDIT_NB

    lines = []
    w = lines.append
    w("# SCALING.md — per-routine distributed scaling audit")
    w("")
    w("Generated in-env by `python tools/gen_scaling.py` on a virtual CPU")
    w(f"mesh (`--xla_force_host_platform_device_count`), P ∈ "
      f"{{{', '.join(str(p) for p in pset)}}}, audit shape n={AUDIT_N} "
      f"(nb={AUDIT_NB}, band kd={AUDIT_KD}, f32, compile-only — nothing "
      "executes; the same XLA SPMD program a TPU mesh compiles).")
    w("")
    w("Columns: **coll bytes** = summed output bytes of every collective op")
    w("in the compiled HLO (all-reduce / all-gather / reduce-scatter /")
    w("all-to-all / collective-permute, async forms folded, per device,")
    w("**static sites** — a collective inside a `while` loop counts once, so")
    w("loop-carried schedules are lower bounds); **flops/dev, bytes/dev** =")
    w("XLA `cost_analysis` of the partitioned module; **comm/compute** =")
    w("collective bytes per device flop.  The audit gates the compiled")
    w("*shape* of each program: a schedule change that widens a gathered")
    w("panel or swaps a psum for an all-gather moves these columns at any")
    w("problem size (the `kernel_plan` discipline of PR 2, generalized from")
    w("Pallas launches to whole distributed programs).")
    w("")
    for nproc in pset:
        w(f"## P = {nproc}")
        w("")
        w("| routine | module | grid | coll bytes | coll sites | collectives "
          "| flops/dev | bytes/dev | comm/compute (B/flop) |")
        w("|---|---|---|---|---|---|---|---|---|")
        for row in rows:
            if row["P"] != nproc:
                continue
            if row.get("skipped"):
                w(f"| {row['routine']} | {row['module']} | {row['grid']} "
                  f"| — | — | n/a ({row['skipped']}) | — | — | — |")
                continue
            if row.get("error"):
                w(f"| {row['routine']} | {row['module']} | {row['grid']} "
                  f"| — | — | ERROR: {row['error'][:80]} | — | — | — |")
                continue
            w(f"| {row['routine']} | {row['module']} | {row['grid']} "
              f"| {_fmt_bytes(row['collective_bytes'])} "
              f"| {row['collective_count']} "
              f"| {_collectives_cell(row)} "
              f"| {row['flops']:.3g} | {row['bytes_accessed']:.3g} "
              f"| {_fmt_ratio(row['comm_compute_ratio'])} |")
        w("")
    w("## Scaling of collective volume with P")
    w("")
    w("| routine | " + " | ".join(f"P={p} coll bytes" for p in pset) + " |")
    w("|---|" + "---|" * len(pset))
    names = []
    for row in rows:
        if row["routine"] not in names:
            names.append(row["routine"])
    by_key = {(r["routine"], r["P"]): r for r in rows}
    for name in names:
        cells = []
        for p in pset:
            r = by_key.get((name, p), {})
            cells.append(_fmt_bytes(r.get("collective_bytes"))
                         if not (r.get("error") or r.get("skipped")) else "—")
        w(f"| {name} | " + " | ".join(cells) + " |")
    w("")
    w("## Two-stage eigensolver at BASELINE scale (from TWOSTAGE_SCALE.md)")
    w("")
    w("The first scaling artifact this file supersedes covered only the")
    w("two-stage path; its compiled `memory_analysis` numbers fold in here")
    w("so one document carries the multi-chip evidence:")
    w("")
    folded = _fold_twostage()
    lines.extend(folded)
    w("")
    w("## CI gate")
    w("")
    w(f"`SCALING_PINS.json` pins the P=2 collective columns; "
      f"`tests/test_perf_pins.py::TestCollectivePins` and the ci.yml "
      f"`scaling-audit` step recompute them and fail when measured bytes "
      f"exceed {BYTES_SLACK}× the pin or the site count grows by more than "
      f"{COUNT_SLACK} (`python tools/gen_scaling.py --check`).  Refresh pins "
      "after an intentional schedule change with `--update-pins`.")
    w("")
    return "\n".join(lines)


def _fold_twostage():
    """Carry TWOSTAGE_SCALE.md's measured tables forward (satellite: fold the
    first scaling artifact's numbers into the generated one)."""
    path = os.path.join(REPO, "TWOSTAGE_SCALE.md")
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return ["*(TWOSTAGE_SCALE.md not present in this checkout)*"]
    # keep the tables + the peak-footprint verdict, drop the H1
    keep = []
    for line in text.splitlines():
        if line.startswith("# "):
            continue
        keep.append(line.replace("## ", "### "))
    return keep


def build_pins(rows, nproc=2):
    routines = {}
    for row in rows:
        if row["P"] != nproc or row.get("error") or row.get("skipped"):
            continue
        routines[row["routine"]] = {
            "collective_bytes": int(row["collective_bytes"]),
            "collective_count": int(row["collective_count"]),
            "flops": float(row["flops"]),
        }
    from slate_tpu.obs.scaling import AUDIT_N, AUDIT_NB

    return {"schema": PINS_SCHEMA, "P": nproc,
            "audit_n": AUDIT_N, "audit_nb": AUDIT_NB,
            "bytes_slack": BYTES_SLACK, "count_slack": COUNT_SLACK,
            "routines": routines}


def check_against_pins(rows, pins) -> int:
    """Diff freshly audited P=2 rows against the pinned envelopes.  Returns
    the number of regressions (0 = gate passes).  The envelope semantics
    live in ``slate_tpu.obs.scaling.check_pins`` — one implementation shared
    with tests/test_perf_pins.py so the two gates cannot drift."""
    from slate_tpu.obs.scaling import check_pins

    problems = check_pins(rows, pins)
    for p in problems:
        print(f"REGRESSION {p}")
    return len(problems)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pset", default="2,4,8",
                    help="comma list of device counts (default 2,4,8)")
    ap.add_argument("--routines", default=None,
                    help="comma list of routine names (default: all)")
    ap.add_argument("--out", default=MD_PATH)
    ap.add_argument("--json", default=None,
                    help="also dump raw audit rows as JSON here")
    ap.add_argument("--update-pins", action="store_true",
                    help="refresh SCALING_PINS.json from the P=2 rows")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: audit P=2 only and diff against "
                         "SCALING_PINS.json; exit nonzero on regression")
    args = ap.parse_args(argv)

    from slate_tpu.obs import scaling

    if args.check and args.routines:
        # the gate diffs against the FULL pin file; auditing a subset would
        # report every unselected routine as a bogus regression
        print("--check audits every pinned routine; drop --routines "
              "(use --update-pins for a subset refresh)")
        return 2
    pset = [2] if args.check else sorted(
        int(p) for p in args.pset.split(",") if p)
    names = ([t for t in args.routines.split(",") if t]
             if args.routines else None)

    def progress(row):
        msg = (row.get("error") or row.get("skipped")
               or f"coll={_fmt_bytes(row['collective_bytes'])} "
                  f"sites={row['collective_count']} "
                  f"flops/dev={row['flops']:.3g}")
        print(f"P={row['P']} {row['routine']:28s} {msg}", flush=True)

    rows = scaling.audit_all(pset, names=names, progress=progress)

    if args.check:
        try:
            with open(PINS_PATH) as f:
                pins = json.load(f)
        except OSError as e:
            print(f"no pins at {PINS_PATH} ({e}); run --update-pins first")
            return 2
        bad = check_against_pins(rows, pins)
        print(f"scaling-audit: {len(rows)} rows, {bad} regressions")
        return 1 if bad else 0

    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1, default=str)
        print(f"wrote {args.json}")

    with open(args.out, "w") as f:
        f.write(render_markdown(rows, pset))
    print(f"wrote {args.out}")

    if args.update_pins:
        pins = build_pins(rows, nproc=2)
        if not pins["routines"]:
            print(f"--update-pins: no P=2 rows audited (pset={pset}); "
                  "refusing to write an empty pin file")
            return 2
        if args.routines:
            # subset refresh: merge into the existing pin file — a partial
            # run must never drop the other routines' envelopes
            try:
                with open(PINS_PATH) as f:
                    prev = json.load(f)
                merged = dict(prev.get("routines", {}))
            except OSError:
                merged = {}
            merged.update(pins["routines"])
            pins["routines"] = merged
        with open(PINS_PATH, "w") as f:
            json.dump(pins, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {PINS_PATH} ({len(pins['routines'])} routines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
