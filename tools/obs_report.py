#!/usr/bin/env python
"""Render OBS_REPORT.md from the exported telemetry artifacts.

Inputs (all JSON documents written by the obs layer):

* ``metrics_timeseries.json`` — the window ring + SLO verdicts
  (schema ``slate_tpu.timeseries/v1``, :mod:`slate_tpu.obs.timeseries`);
* ``metrics.json`` — the cumulative registry document (schema
  ``slate_tpu.metrics/v1``), source of the per-routine stage-latency
  decomposition;
* optionally a flight-recorder dump (schema ``slate_tpu.flight/v1``).

Output: one markdown report — per-routine stage-latency decomposition
(queue-wait vs execute vs pad, p50/p99 from the histogram buckets), the
per-executor utilization table (with pad-waste and slot-join/staged-merge
continuous-batching counts), the padding-waste table per (routine, bucket),
window request/batch/error rates, the SLO verdict table, the rejection
breakdown (shed / deadline-expired / worker-failed requests grouped by
reason and lane), and the flight-recorder summary.  The CI serving-smoke step writes it next to the artifacts it
renders; ``render_report`` is importable so the smoke gates on the same
numbers it publishes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _load(path: Optional[str]) -> Optional[Dict[str, Any]]:
    if not path:
        return None
    with open(path) as f:
        return json.load(f)


def _hist_samples(metrics_doc: Dict[str, Any], name: str
                  ) -> List[Dict[str, Any]]:
    for m in metrics_doc.get("metrics", ()):
        if m["name"] == name and m["kind"] == "histogram":
            return m["samples"]
    return []


def _merge_counts(samples: List[Dict[str, Any]],
                  routine: Optional[str] = None
                  ) -> Optional[Tuple[List[float], List[float]]]:
    """Sum histogram counts across samples (optionally filtered to one
    routine label via ``routine`` or ``driver``); None when nothing
    matches."""
    buckets: Optional[List[float]] = None
    counts: Optional[List[float]] = None
    for s in samples:
        lab = s.get("labels", {})
        if routine is not None and routine not in (lab.get("routine"),
                                                   lab.get("driver")):
            continue
        if buckets is None:
            buckets, counts = list(s["buckets"]), [0.0] * len(s["counts"])
        if list(s["buckets"]) != buckets:
            continue                 # mixed bucket tables never merge
        counts = [a + b for a, b in zip(counts, s["counts"])]
    if counts is None or sum(counts) <= 0:
        return None
    return buckets, counts


def _pcts(merged) -> str:
    from slate_tpu.obs import quantile_from_counts

    if merged is None:
        return "—"
    buckets, counts = merged
    p50 = quantile_from_counts(buckets, counts, 0.50)
    p99 = quantile_from_counts(buckets, counts, 0.99)
    return f"{p50 * 1e3:.2f} / {p99 * 1e3:.2f}"


#: stage -> histogram family (the decomposition's columns)
STAGE_HISTS = (
    ("queue-wait", "slate_serve_queue_wait_seconds"),
    ("pad", "slate_serve_pad_seconds"),
    ("execute", "slate_serve_execute_seconds"),
    ("total", "slate_serve_latency_seconds"),
)


def _stage_table(metrics_doc: Dict[str, Any]) -> List[str]:
    routines = sorted({
        s["labels"].get("routine", s["labels"].get("driver", "?"))
        for s in _hist_samples(metrics_doc, "slate_serve_latency_seconds")})
    if not routines:
        return ["_no serving traffic recorded_", ""]
    lines = ["| routine | " + " | ".join(
        f"{name} p50/p99 (ms)" for name, _ in STAGE_HISTS) + " |",
        "|---|" + "---|" * len(STAGE_HISTS)]
    for r in routines:
        cells = []
        for _, hist in STAGE_HISTS:
            samples = _hist_samples(metrics_doc, hist)
            cells.append(_pcts(_merge_counts(samples, routine=r)))
        lines.append(f"| `{r}` | " + " | ".join(cells) + " |")
    lines.append("")
    lines.append("(execute = device time with the cache share subtracted "
                 "and the result blocked on; batch-level stages are "
                 "attributed to every request in the batch)")
    return lines + [""]


def _counter_sum(metrics_doc: Dict[str, Any], name: str,
                 **labels: str) -> float:
    for m in metrics_doc.get("metrics", ()):
        if m["name"] == name and m["kind"] == "counter":
            return sum(s["value"] for s in m["samples"]
                       if all(s.get("labels", {}).get(k) == v
                              for k, v in labels.items()))
    return 0.0


def _counter_samples(metrics_doc: Dict[str, Any], name: str
                     ) -> List[Dict[str, Any]]:
    for m in metrics_doc.get("metrics", ()):
        if m["name"] == name and m["kind"] == "counter":
            return m["samples"]
    return []


def _pad_waste_table(metrics_doc: Dict[str, Any]) -> List[str]:
    """Padding waste per (routine, bucket): dispatch-time padded-but-not-
    real operand elements (shape pad inside real slots + whole ghost
    slots) with the pad-fraction distribution — the signal the bucket-
    boundary tuner (ROADMAP 3(a)) reads."""
    samples = _counter_samples(metrics_doc,
                               "slate_serve_pad_waste_elems_total")
    if not samples:
        return ["_no pad-waste samples recorded_", ""]
    groups: Dict[Tuple[str, str], float] = {}
    for s in samples:
        lab = s.get("labels", {})
        k = (lab.get("routine", "?"), lab.get("bucket", "?"))
        groups[k] = groups.get(k, 0.0) + s["value"]
    frac = _hist_samples(metrics_doc, "slate_serve_pad_fraction")
    lines = ["| routine | bucket | pad waste (elems) "
             "| pad fraction p50/p99 |", "|---|---|---|---|"]
    from slate_tpu.obs import quantile_from_counts

    for (r, b), v in sorted(groups.items()):
        merged = _merge_counts(
            [s for s in frac
             if s.get("labels", {}).get("routine") == r
             and s.get("labels", {}).get("bucket") == b])
        if merged is None:
            cell = "—"
        else:
            p50 = quantile_from_counts(*merged, 0.50)
            p99 = quantile_from_counts(*merged, 0.99)
            cell = f"{p50:.2f} / {p99:.2f}"
        lines.append(f"| `{r}` | `{b}` | {int(v)} | {cell} |")
    lines += ["", "(waste = operand elements carrying no real data at "
              "dispatch; fraction = waste over the batch's total padded "
              "elements — high fractions mark bucket boundaries worth "
              "re-tuning)", ""]
    return lines


def _executor_table(metrics_doc: Dict[str, Any]) -> List[str]:
    """Per-executor utilization: device-busy and pad time from the
    ``executor``-labelled stage histograms, batch count, cache traffic
    (hits / misses == compiles) from the owner-labelled cache counters,
    and each executor's share of the pool's total busy time — the skew
    view residency-aware routing and work-stealing are audited with."""
    ex_samples = _hist_samples(metrics_doc, "slate_serve_execute_seconds")
    pad_samples = _hist_samples(metrics_doc, "slate_serve_pad_seconds")
    names = sorted({s["labels"]["executor"] for s in ex_samples
                    if s.get("labels", {}).get("executor")})
    if not names:
        return ["_no per-executor samples (single-worker serve path or no "
                "traffic)_", ""]

    def busy(samples, ex):
        tot_s = sum(s["sum"] for s in samples
                    if s.get("labels", {}).get("executor") == ex)
        n = sum(s["count"] for s in samples
                if s.get("labels", {}).get("executor") == ex)
        return tot_s, n

    pool_busy = sum(busy(ex_samples, ex)[0] for ex in names) or 1.0
    lines = ["| executor | batches | busy (s) | ms/batch | pad (s) "
             "| pad waste | cache hit | compile | busy share |",
             "|---|---|---|---|---|---|---|---|---|"]
    for ex in names:
        b_s, b_n = busy(ex_samples, ex)
        p_s, _ = busy(pad_samples, ex)
        waste = _counter_sum(metrics_doc,
                             "slate_serve_pad_waste_elems_total",
                             executor=ex)
        hits = _counter_sum(metrics_doc, "slate_serve_cache_hits_total",
                            executor=ex)
        miss = _counter_sum(metrics_doc, "slate_serve_cache_misses_total",
                            executor=ex)
        per = f"{b_s / b_n * 1e3:.2f}" if b_n else "—"
        lines.append(f"| `{ex}` | {int(b_n)} | {b_s:.3f} | {per} "
                     f"| {p_s:.3f} | {int(waste)} | {int(hits)} "
                     f"| {int(miss)} | {b_s / pool_busy:.0%} |")
    steals = _counter_sum(metrics_doc, "slate_serve_steals_total")
    requeued = _counter_sum(metrics_doc, "slate_serve_requeued_chunks_total")
    joins = _counter_sum(metrics_doc, "slate_serve_slot_joins_total")
    merges = _counter_sum(metrics_doc, "slate_serve_staged_merges_total")
    lines += ["", f"({len(names)} executors; {int(steals)} chunks "
              f"work-stolen, {int(requeued)} requeued by death drains, "
              f"{int(joins)} requests slot-joined + {int(merges)} chunks "
              "staged-merged (continuous batching); busy share = this "
              "executor's device time over the pool's; pad waste = padded "
              "elements carrying no real data)",
              ""]
    return lines


def _rate(window: Dict[str, Any], counter: str) -> float:
    return sum(c["rate"] for c in window["counters"]
               if c["name"] == counter)


def _window_table(ts_doc: Dict[str, Any], max_rows: int = 30) -> List[str]:
    ws = ts_doc.get("windows", [])
    if not ws:
        return ["_no windows sampled_", ""]
    t0 = ws[0]["t_start"]
    lines = ["| window | t+ (s) | dur (s) | req/s | batch/s | err/s | "
             "p99 lat (ms) |", "|---|---|---|---|---|---|---|"]
    shown = ws[-max_rows:]
    for w in shown:
        p99 = None
        merged = _merge_counts(
            [h for h in w["histograms"]
             if h["name"] == "slate_serve_latency_seconds"])
        if merged is not None:
            from slate_tpu.obs import quantile_from_counts

            p99 = quantile_from_counts(*merged, 0.99)
        p99_cell = f"{p99 * 1e3:.2f}" if p99 is not None else "—"
        lines.append(
            f"| {w['index']} | {w['t_start'] - t0:.2f} "
            f"| {w['duration_s']:.2f} "
            f"| {_rate(w, 'slate_serve_requests_total'):.1f} "
            f"| {_rate(w, 'slate_serve_batches_total'):.1f} "
            f"| {_rate(w, 'slate_serve_worker_errors_total'):.2f} "
            f"| {p99_cell} |")
    if len(ws) > max_rows:
        lines.append(f"| … | | | | | | ({len(ws) - max_rows} older windows "
                     "elided) |")
    return lines + [""]


_VERDICT_MARK = {"ok": "✅ ok", "warning": "⚠️ warning", "breach": "❌ breach",
                 "no_data": "∅ no data"}


def _slo_table(ts_doc: Dict[str, Any]) -> List[str]:
    slos = ts_doc.get("slos")
    if not slos:
        return ["_no SLOs evaluated_", ""]
    lines = ["| SLO | kind | verdict | burn rate | detail |",
             "|---|---|---|---|---|"]
    for v in slos:
        burn = v.get("burn_rate")
        burn_cell = f"{burn:.2f}" if burn is not None else "—"
        lines.append(
            f"| `{v['name']}` | {v.get('kind', '?')} "
            f"| {_VERDICT_MARK.get(v['verdict'], v['verdict'])} "
            f"| {burn_cell} | {v.get('detail', '')} |")
    return lines + [""]


def _rejection_table(flight_doc: Optional[Dict[str, Any]]) -> List[str]:
    """Rejection breakdown: every flight record carrying a ``reason``
    (shed / deadline / worker_error / worker_death), grouped by
    (reason, lane) — the "where did the shed land" table the overload
    contract is audited against."""
    if flight_doc is None:
        return ["_no flight-recorder dump supplied_", ""]
    recs = [r for r in flight_doc.get("records", []) if r.get("reason")]
    if not recs:
        return ["_no rejected/expired requests in the ring_", ""]
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for r in recs:
        groups.setdefault((r["reason"], r.get("lane") or "?"), []).append(r)
    lines = ["| reason | lane | count | routines | example trace id |",
             "|---|---|---|---|---|"]
    for (reason, lane), rs in sorted(groups.items()):
        routines = ",".join(sorted({r["routine"] for r in rs}))
        lines.append(f"| `{reason}` | `{lane}` | {len(rs)} | {routines} "
                     f"| `{rs[-1]['trace_id']}` |")
    lines.append("")
    lines.append(f"({len(recs)} rejected/expired records of "
                 f"{len(flight_doc.get('records', []))} in the ring; every "
                 "rejection leaves a record with its reason — `shed` = "
                 "admission control, `deadline` = in-queue expiry, "
                 "`worker_error`/`worker_death` = executor failure)")
    return lines + [""]


def _flight_section(flight_doc: Optional[Dict[str, Any]]) -> List[str]:
    if flight_doc is None:
        return ["_no flight-recorder dump supplied_", ""]
    recs = flight_doc.get("records", [])
    exhausted = [r for r in recs if r.get("exhausted")]
    errors = [r for r in recs if r.get("error")]
    lines = [f"{len(recs)} records in the ring "
             f"(capacity {flight_doc.get('capacity', '?')}, dump reason "
             f"`{flight_doc.get('reason', '?')}`): "
             f"{len(exhausted)} ladder-exhausted, "
             f"{len(errors)} worker-error.", ""]
    for r in (exhausted or errors)[-3:]:
        stages = ", ".join(f"{k}={v * 1e3:.2f}ms"
                           for k, v in r.get("stages", {}).items())
        lines.append(f"* `{r['trace_id']}` {r['routine']}@{r['bucket']} "
                     f"info={r.get('info')} ladder={r.get('ladder')} "
                     f"error={r.get('error')} — {stages}")
    if exhausted or errors:
        lines.append("")
    return lines


def render_report(ts_doc: Dict[str, Any],
                  metrics_doc: Optional[Dict[str, Any]] = None,
                  flight_doc: Optional[Dict[str, Any]] = None) -> str:
    ws = ts_doc.get("windows", [])
    span = (ws[-1]["t_end"] - ws[0]["t_start"]) if ws else 0.0
    md = [
        "# OBS_REPORT — serving telemetry",
        "",
        f"Source `{ts_doc.get('source', '?')}` · {len(ws)} windows over "
        f"{span:.2f}s (interval {ts_doc.get('interval_s', '?')}s) · "
        "generated by `tools/obs_report.py` from "
        "`metrics_timeseries.json` (+ `metrics.json`, flight dump).",
        "",
        "## SLO verdicts",
        "",
        *_slo_table(ts_doc),
        "## Per-routine stage-latency decomposition",
        "",
    ]
    if metrics_doc is not None:
        md += _stage_table(metrics_doc)
        md += ["## Per-executor utilization", "",
               *_executor_table(metrics_doc)]
        md += ["## Padding waste", "", *_pad_waste_table(metrics_doc)]
    else:
        md += ["_no metrics.json supplied_", ""]
    md += ["## Window rates", "", *_window_table(ts_doc),
           "## Rejection breakdown", "", *_rejection_table(flight_doc),
           "## Flight recorder", "", *_flight_section(flight_doc)]
    return "\n".join(md).rstrip() + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--timeseries", default="metrics_timeseries.json",
                    help="metrics_timeseries.json path")
    ap.add_argument("--metrics", default=None, help="metrics.json path")
    ap.add_argument("--flight", default=None, help="flight dump path")
    ap.add_argument("--out", default="OBS_REPORT.md", help="output path")
    args = ap.parse_args(argv)

    from slate_tpu.obs import validate_timeseries

    ts_doc = _load(args.timeseries)
    validate_timeseries(ts_doc)
    report = render_report(ts_doc, _load(args.metrics), _load(args.flight))
    with open(args.out, "w") as f:
        f.write(report)
    print(f"wrote {args.out}: {len(ts_doc.get('windows', []))} windows, "
          f"{len(ts_doc.get('slos') or [])} SLO verdicts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
