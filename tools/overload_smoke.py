#!/usr/bin/env python
"""CI overload-smoke: a short seeded overload run on CPU, gating on the
overload-survival contract (ISSUE 15 / ROADMAP 2(c)).

The serving queue is driven at ~2x its *measured* capacity with
heavy-tailed arrivals across the three priority lanes (seeded, CPU-only,
~60 s wall).  Gates (the ci.yml ``overload-smoke`` step fails on any):

* the interactive-lane p99 latency SLO evaluates NON-BREACH under overload
  (the whole point of lanes + shedding: interactive traffic survives),
* load shedding actually happened and landed on the right lane: >= 1% of
  offered best-effort traffic rejected with ``QueueOverloadError``, and
  ZERO interactive submissions shed at the calibrated policy,
* deadline machinery leaves evidence: ``slate_serve_deadline_expired_total``
  present (a deterministic expiry scenario guarantees the counter exists
  even on a fast runner),
* ``slate_serve_shed_total`` present and the whole registry schema-valid,
* zero unresolved tickets — every admitted request resolved (value or
  typed error); nothing hung past the drain,
* every rejected/expired request in the flight ring carries its matching
  ``reason`` (``shed`` / ``deadline``), and OBS_REPORT.md renders the
  rejection-breakdown table.

Artifacts: ``overload_metrics.json``, ``overload_timeseries.json``,
``overload_flight.json``, ``OVERLOAD_REPORT.md``.  Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from force_cpu import force_cpu_backend  # noqa: E402

force_cpu_backend()

DURATION_S = 20.0
INTERACTIVE_P99_S = 2.5        # generous for CI runners; the lane contract
MIN_BEST_EFFORT_SHED = 0.01    # >= 1% of offered best-effort traffic


def main() -> int:
    import numpy as np

    from slate_tpu import obs, serve
    from slate_tpu.core.exceptions import DeadlineExceededError

    import obs_report

    flight = serve.FlightRecorder(capacity=50_000, auto_dump_path="/dev/null")
    sampler = obs.TimeSeriesSampler(interval_s=0.25)
    monitor_box = {}

    def after_warmup(q):
        sampler.start()
        monitor_box["monitor"] = obs.SLOMonitor([obs.SLO(
            name="interactive_p99_latency", kind="latency",
            metric="slate_serve_latency_seconds",
            labels=(("lane", "interactive"),),
            objective=INTERACTIVE_P99_S, target=0.99, windows=10_000)],
            sampler)
        q.attach_slo(monitor_box["monitor"])

    stats = serve.run_overload_workload(
        duration_s=DURATION_S, seed=0, flight=flight,
        after_warmup=after_warmup)

    # deterministic deadline-expiry scenario: the counter must exist even if
    # the overload pass's best-effort traffic happened to beat its budgets.
    # A slow_executor chaos fault stalls the worker on an interactive batch
    # long past the best-effort ticket's budget, so the expiry is certain.
    from slate_tpu import robust

    q = serve.ServeQueue(flight=flight)
    a = np.eye(8, dtype=np.float32) * 8
    b = np.ones((8, 1), np.float32)
    expired_typed = False
    with robust.FaultPlan([robust.FaultSpec(
            serve.SERVE_SITE, "slow_executor", call_index=0, delay_s=0.5)]):
        t_slow = q.submit("gesv", a, b)                 # stalls the worker
        time.sleep(0.05)                                # let it get popped
        t = q.submit("gesv", a, b, lane="best_effort", deadline=0.05)
        t_slow.result(timeout=30.0)
        try:
            t.result(timeout=30.0)
        except DeadlineExceededError:
            expired_typed = True
    q.close()

    sampler.stop()
    verdicts = monitor_box["monitor"].evaluate()

    failures = []
    # -- the lane contract ---------------------------------------------------
    (iv,) = [v for v in verdicts if v.name == "interactive_p99_latency"]
    if iv.verdict == "breach":
        failures.append(f"interactive p99 SLO BREACH under overload "
                        f"({iv.detail})")
    if iv.verdict == "no_data":
        failures.append("interactive p99 SLO has no data — lane label "
                        "missing from the latency histogram?")
    be_offered = stats["submitted_by_lane"].get("best_effort", 0)
    be_shed = stats["shed_by_lane"].get("best_effort", 0)
    if be_offered == 0:
        failures.append("no best-effort traffic offered")
    elif be_shed < MIN_BEST_EFFORT_SHED * be_offered:
        failures.append(f"best-effort shed {be_shed}/{be_offered} — "
                        "under the 1% overload floor; shedding not engaging")
    if stats["shed_by_lane"].get("interactive", 0):
        failures.append(f"{stats['shed_by_lane']['interactive']} interactive "
                        "requests shed — the ladder landed on the WRONG lane")
    if stats["hung"]:
        failures.append(f"{stats['hung']} tickets unresolved after drain")
    if stats["worker_failed"]:
        failures.append(f"{stats['worker_failed']} requests died on "
                        "unexpected worker errors")
    if not expired_typed:
        failures.append("deterministic deadline scenario did not raise "
                        "DeadlineExceededError")

    # -- counters + schema ---------------------------------------------------
    doc = obs.metrics_doc(source="overload-smoke")
    try:
        obs.validate_metrics(doc)
    except ValueError as e:
        failures.append(f"metrics schema violation: {e}")
    names = {m["name"] for m in doc["metrics"]}
    for need in ("slate_serve_shed_total",
                 "slate_serve_deadline_expired_total",
                 "slate_serve_lane_depth"):
        if need not in names:
            failures.append(f"metric {need} missing from the registry")
    obs.export_metrics("overload_metrics.json", source="overload-smoke")

    # -- flight evidence -----------------------------------------------------
    recs = flight.records()
    shed_recs = [r for r in recs if r.reason == "shed"]
    reg_shed = stats["shed"]
    if len(shed_recs) < reg_shed:
        failures.append(f"only {len(shed_recs)} shed flight records for "
                        f"{reg_shed} rejections — rejections without "
                        "evidence")
    for r in shed_recs[:50]:
        if "QueueOverloadError" not in (r.error or ""):
            failures.append(f"shed record {r.trace_id} lacks the typed "
                            f"error: {r.error!r}")
            break
    if not any(r.reason == "deadline" for r in recs):
        failures.append("no deadline flight record despite the "
                        "deterministic expiry")

    ts_path = sampler.export("overload_timeseries.json",
                             source="overload-smoke",
                             slos=[v.to_dict() for v in verdicts])
    ts_doc = json.load(open(ts_path))
    try:
        obs.validate_timeseries(ts_doc)
    except ValueError as e:
        failures.append(f"timeseries schema violation: {e}")
    flight_path = flight.dump("overload_flight.json")
    report = obs_report.render_report(ts_doc, doc,
                                      json.load(open(flight_path)))
    with open("OVERLOAD_REPORT.md", "w") as f:
        f.write(report)
    if "## Rejection breakdown" not in report or "| `shed` |" not in report:
        failures.append("OVERLOAD_REPORT.md missing the rejection-"
                        "breakdown table")

    print(json.dumps({
        "ok": not failures,
        "capacity_solves_per_sec": stats["capacity_solves_per_sec"],
        "offered_rate": stats["offered_rate"],
        "admitted": stats["admitted"], "ok_requests": stats["ok"],
        "shed_by_lane": stats["shed_by_lane"],
        "shed_reasons": stats["shed_reasons"],
        "expired": stats["expired"], "hung": stats["hung"],
        "interactive_p99_ms": stats.get("interactive_p99_ms"),
        "best_effort_p99_ms": stats.get("best_effort_p99_ms"),
        "slo": {v.name: v.verdict for v in verdicts},
        "artifacts": ["overload_metrics.json", "overload_timeseries.json",
                      "overload_flight.json", "OVERLOAD_REPORT.md"],
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
