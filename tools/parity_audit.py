#!/usr/bin/env python3
"""Parity audit: every public routine of the reference's slate.hh checked
against the slate_tpu surface (top-level, linalg, blas, parallel, simplified),
PLUS behavior checks — names alone would pass a stub (VERDICT r5 weak #6), so
the audit also executes the method/option surface:

* ``MethodLU.CALU`` vs ``MethodLU.PartialPiv`` must produce genuinely
  different pivot paths (different permutations, both factoring to eps);
* ``Options.lu_panel`` must route ("pp" vs "tournament" pivot paths differ;
  an invalid value raises rather than being silently ignored);
* ``lookahead`` / ``block_size`` Options must be accepted AND consumed
  (block_size reaches the blocked CALU driver — distinct compiled variants;
  lookahead reaches potrf's dispatch).

Run:  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python tools/parity_audit.py

Exit status 0 iff every reference routine resolves and every behavior check
passes.  Names the framework deliberately re-spells are listed in RENAMES
(the audit follows them); anything else must exist under the reference's own
name.
"""

from __future__ import annotations

import os
import re
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _TOOLS)
sys.path.insert(0, os.path.dirname(_TOOLS))     # repo root for slate_tpu
from force_cpu import force_cpu_backend  # noqa: E402

# 8 virtual devices: the lookahead behavior check routes potrf through a
# real 2x4 process grid (the mesh is where Option::Lookahead is observable)
force_cpu_backend(virtual_devices=8)

REF_HEADER = "/root/reference/include/slate/slate.hh"

# reference name -> where we provide it under a different spelling
# (set_lambdas/set_from_function cover the reference's lambda-set overload)
RENAMES = {
    "gesvd": "svd",                 # the reference itself aliases gesvd -> svd
    "colNorms": "col_norms",
}
NOT_ROUTINES = {"scalar_t"}         # artifacts of the header scrape


def reference_routines():
    names = set()
    # [A-Za-z0-9_] in the capture: camelCase drivers (trsmA, gemmC, hemmA,
    # colNorms) are real public routines — the round-4 pattern silently
    # dropped them from the audit
    pat = re.compile(r"^[A-Za-z0-9_:<>,& ]*?\b([a-z][A-Za-z0-9_]*)\s*\(")
    with open(REF_HEADER) as f:
        for line in f:
            m = pat.match(line)
            if m:
                names.add(m.group(1))
    return sorted(names - NOT_ROUTINES)


def resolve(name: str):
    import slate_tpu
    from slate_tpu import blas, linalg, parallel, simplified

    target = RENAMES.get(name, name)
    for mod in (slate_tpu, linalg, blas, simplified, parallel):
        if hasattr(mod, target):
            return f"{mod.__name__}.{target}"
        if hasattr(mod, target + "_distributed"):
            return f"{mod.__name__}.{target}_distributed"
    return None


def behavior_checks() -> "tuple[list, int]":
    """Execute the method/option surface; returns (failure strings, number of
    checks run) — empty failures = all pass.

    One notch past hasattr: each check runs the real driver and asserts the
    OBSERVABLE difference the option is supposed to make."""
    import numpy as np
    import jax.numpy as jnp

    import slate_tpu
    from slate_tpu import linalg
    from slate_tpu.core.exceptions import SlateError
    from slate_tpu.core.types import MethodLU, Options

    failures = []
    nchecks = 0
    rng = np.random.default_rng(0)
    n = 64
    A = rng.standard_normal((n, n)).astype(np.float32)

    def lu_ok(a, lu_arr, perm):
        lu_np = np.asarray(lu_arr)
        L = np.tril(lu_np, -1) + np.eye(n, dtype=lu_np.dtype)
        U = np.triu(lu_np)
        return (np.linalg.norm(a[np.asarray(perm)] - L @ U)
                / np.linalg.norm(a)) < 1e-4

    # --- MethodLU.CALU vs PartialPiv: different pivot PATHS, same contract
    nchecks += 3
    lu_pp, perm_pp, info_pp = linalg.getrf(A.copy(),
                                           {"method_lu": "partialpiv"})
    lu_ca, perm_ca, info_ca = linalg.getrf(
        A.copy(), {"method_lu": "calu", "block_size": 16,
                   "inner_blocking": 8})
    if int(info_pp) or not lu_ok(A, lu_pp, perm_pp):
        failures.append("MethodLU.PartialPiv does not factor correctly")
    if int(info_ca) or not lu_ok(A, lu_ca, perm_ca):
        failures.append("MethodLU.CALU does not factor correctly")
    if np.asarray(perm_pp).tolist() == np.asarray(perm_ca).tolist():
        failures.append("CALU and PartialPiv returned identical pivot paths "
                        "— the method enum is not routing")

    # --- lu_panel="pp" vs "tournament": different pivot paths under CALU
    nchecks += 2
    base = {"method_lu": "calu", "block_size": 16, "inner_blocking": 8}
    _, perm_t, _ = linalg.getrf(A.copy(), dict(base, lu_panel="tournament"))
    _, perm_p, info_p = linalg.getrf(A.copy(), dict(base, lu_panel="pp"))
    if int(info_p) or np.asarray(perm_t).tolist() == np.asarray(perm_p).tolist():
        failures.append("lu_panel='pp' does not change the pivot path "
                        "(silently ignored?)")
    try:
        linalg.getrf(A.copy(), dict(base, lu_panel="bogus"))
        failures.append("invalid lu_panel accepted silently")
    except SlateError:
        pass

    # --- block_size is consumed: distinct compiled CALU variants per nb
    nchecks += 1
    from slate_tpu.linalg.lu import _getrf_tntpiv_fn

    before = _getrf_tntpiv_fn.cache_info().currsize
    linalg.getrf(A.copy(), dict(base, block_size=24, inner_blocking=24))
    linalg.getrf(A.copy(), dict(base, block_size=32, inner_blocking=32))
    after = _getrf_tntpiv_fn.cache_info().currsize
    if after - before < 2:
        failures.append("Options.block_size does not reach the blocked CALU "
                        "driver (no per-nb compiled variants)")

    # --- lookahead / block_size accepted by Options and potrf's dispatch
    nchecks += 2
    try:
        o = Options.make({"lookahead": 3, "block_size": 128})
        if o.lookahead != 3 or o.block_size != 128:
            failures.append("Options dropped lookahead/block_size values")
    except Exception as e:  # noqa: BLE001
        failures.append(f"Options rejected lookahead/block_size: {e}")
    # lookahead is OBSERVED, not grepped: Options(lookahead>=2) on a
    # grid-bound potrf must actually reach the explicit pipeline
    # (potrf_distributed's dispatch) — probe by instrumenting the pipeline
    # entry point the dispatch imports at call time
    import slate_tpu.parallel.pipeline as pipe_mod
    from slate_tpu.parallel import ProcessGrid

    hits = []
    orig = pipe_mod.potrf_pipelined

    def probe(Af, grid, nb=256):
        hits.append(1)
        return orig(Af, grid, nb=nb)

    pipe_mod.potrf_pipelined = probe
    try:
        G = rng.standard_normal((32, 32)).astype(np.float32)
        spd = (G @ G.T + 32 * np.eye(32, dtype=np.float32))
        M = slate_tpu.HermitianMatrix.from_array(
            "lower", spd, nb=8, grid=ProcessGrid(2, 4))
        L, info_la = slate_tpu.potrf(M, opts={"lookahead": 2, "block_size": 8})
        res = np.linalg.norm(spd - np.tril(np.asarray(L))
                             @ np.tril(np.asarray(L)).T) / np.linalg.norm(spd)
        if not hits:
            failures.append("Options.lookahead>=2 did not route potrf to the "
                            "explicit pipeline (silently ignored)")
        elif res > 1e-4:
            failures.append(f"lookahead pipeline potrf incorrect (res={res:.1e})")
    except Exception as e:  # noqa: BLE001
        failures.append(f"lookahead-routing probe crashed: {e}")
    finally:
        pipe_mod.potrf_pipelined = orig
    return failures, nchecks


def main() -> int:
    rc = 0
    if os.path.exists(REF_HEADER):
        missing = []
        rows = []
        for name in reference_routines():
            where = resolve(name)
            rows.append((name, where or "MISSING"))
            if where is None:
                missing.append(name)
        width = max(len(n) for n, _ in rows)
        for name, where in rows:
            print(f"{name:<{width}}  {where}")
        print(f"\n{len(rows) - len(missing)}/{len(rows)} reference routines "
              "covered")
        if missing:
            print("MISSING:", ", ".join(missing))
            rc = 1
    else:
        # the behavior half needs no reference checkout — run it anywhere
        print(f"name audit skipped: {REF_HEADER} not mounted")
    fails, nchecks = behavior_checks()
    print(f"behavior: {max(nchecks - len(fails), 0)}/{nchecks} checks pass "
          "(method routing, lu_panel, option plumbing)")
    for f in fails:
        print("BEHAVIOR FAIL:", f)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
