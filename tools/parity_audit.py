#!/usr/bin/env python3
"""Parity audit: every public routine of the reference's slate.hh checked
against the slate_tpu surface (top-level, linalg, blas, parallel, simplified).

Run:  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python tools/parity_audit.py

Exit status 0 iff every reference routine resolves.  Names the framework
deliberately re-spells are listed in RENAMES (the audit follows them);
anything else must exist under the reference's own name.
"""

from __future__ import annotations

import os
import re
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _TOOLS)
sys.path.insert(0, os.path.dirname(_TOOLS))     # repo root for slate_tpu
from force_cpu import force_cpu_backend  # noqa: E402

force_cpu_backend(virtual_devices=1)

REF_HEADER = "/root/reference/include/slate/slate.hh"

# reference name -> where we provide it under a different spelling
# (set_lambdas/set_from_function cover the reference's lambda-set overload)
RENAMES = {
    "gesvd": "svd",                 # the reference itself aliases gesvd -> svd
    "colNorms": "col_norms",
}
NOT_ROUTINES = {"scalar_t"}         # artifacts of the header scrape


def reference_routines():
    names = set()
    # [A-Za-z0-9_] in the capture: camelCase drivers (trsmA, gemmC, hemmA,
    # colNorms) are real public routines — the round-4 pattern silently
    # dropped them from the audit
    pat = re.compile(r"^[A-Za-z0-9_:<>,& ]*?\b([a-z][A-Za-z0-9_]*)\s*\(")
    with open(REF_HEADER) as f:
        for line in f:
            m = pat.match(line)
            if m:
                names.add(m.group(1))
    return sorted(names - NOT_ROUTINES)


def resolve(name: str):
    import slate_tpu
    from slate_tpu import blas, linalg, parallel, simplified

    target = RENAMES.get(name, name)
    for mod in (slate_tpu, linalg, blas, simplified, parallel):
        if hasattr(mod, target):
            return f"{mod.__name__}.{target}"
        if hasattr(mod, target + "_distributed"):
            return f"{mod.__name__}.{target}_distributed"
    return None


def main() -> int:
    missing = []
    rows = []
    for name in reference_routines():
        where = resolve(name)
        rows.append((name, where or "MISSING"))
        if where is None:
            missing.append(name)
    width = max(len(n) for n, _ in rows)
    for name, where in rows:
        print(f"{name:<{width}}  {where}")
    print(f"\n{len(rows) - len(missing)}/{len(rows)} reference routines covered")
    if missing:
        print("MISSING:", ", ".join(missing))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
