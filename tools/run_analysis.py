#!/usr/bin/env python
"""slate-lint entry point: static analysis + collective race audit.

Thin wrapper over ``python -m slate_tpu.analysis`` (one shared main) that
first pins the virtual CPU mesh — the Tier B collective-ordering audit
AOT-compiles every distributed routine in the obs/scaling registry, so it
needs ``--xla_force_host_platform_device_count`` set before jax initializes
(the same bootstrap as tools/gen_scaling.py).

Usage::

    python tools/run_analysis.py --check                   # AST gate
    python tools/run_analysis.py --collectives --pset 2    # CI ordering audit
    python tools/run_analysis.py --collectives --pset 2,4,8
    python tools/run_analysis.py --rules                   # rule table
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from force_cpu import force_cpu_backend

force_cpu_backend(virtual_devices=8)

from slate_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
