#!/usr/bin/env python
"""Two-process jax.distributed CPU tier — the analogue of the reference CI's
``mpirun -np 4`` runs (.github/workflows/test.sh:48): the same SPMD code path
with a real multi-*process* world, catching cross-host bugs (global vs local
device indexing, process-spanning collectives) that the single-process
8-device mesh cannot.

Launches 2 worker processes (this script re-execs itself with --worker), each
owning 4 virtual CPU devices, forming one 8-device global mesh spanning the
process boundary.  Each worker runs:

- a global psum over all 8 devices (the cross-process collective floor),
- a (2, 4) process-grid SUMMA gemm whose row axis spans the two processes,
- a distributed Cholesky solve through the same ProcessGrid the in-process
  tests use, validating the grid code is process-count agnostic.

Exit code 0 = both workers verified their shard of the results.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

NPROC = 2
LOCAL_DEVICES = 4


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(coord: str, pid: int) -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    sys.path.insert(0, os.path.join(root, "tools"))
    from force_cpu import force_cpu_backend  # shared TPU-plugin defense

    # each worker must own exactly LOCAL_DEVICES virtual devices; an ambient
    # device-count flag (e.g. the test-suite's =8) would win inside
    # force_cpu_backend's already-present check, so strip it first
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", flags)
    force_cpu_backend(virtual_devices=LOCAL_DEVICES)
    import jax

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=NPROC, process_id=pid)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    assert len(devs) == NPROC * LOCAL_DEVICES, f"global devices: {len(devs)}"
    assert len(jax.local_devices()) == LOCAL_DEVICES

    # --- 1) global psum across the process boundary -------------------------
    mesh = Mesh(np.array(devs).reshape(NPROC, LOCAL_DEVICES), ("p", "q"))
    flat = Mesh(np.array(devs), ("d",))

    @jax.jit
    def allsum(x):
        def body(s):
            return jax.lax.psum(s, "d")
        return shard_map(body, mesh=flat, in_specs=P("d"), out_specs=P())(x)

    n = NPROC * LOCAL_DEVICES
    x = jnp.arange(n, dtype=jnp.float32)
    xs = jax.device_put(x, NamedSharding(flat, P("d")))
    total = allsum(xs)
    # out_specs=P() replicates the scalar to every device; read this
    # process's addressable copy (a cross-process float() would need a gather)
    got = float(np.asarray(total.addressable_shards[0].data))
    assert got == n * (n - 1) / 2, got

    # --- 2) SUMMA gemm on the (2, 4) grid spanning both processes -----------
    from slate_tpu.parallel import ProcessGrid, gemm_allgather

    grid = ProcessGrid(NPROC, LOCAL_DEVICES, devices=devs)
    rng = np.random.default_rng(0)            # same seed -> same global operands
    m = k = nn = 32
    A = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    B = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32))
    C = gemm_allgather(A, B, grid)
    ref = np.asarray(A) @ np.asarray(B)
    for shard in C.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, ref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-4)
    print(f"worker {pid}: summa OK", flush=True)

    # --- 3) distributed Cholesky solve through the same grid ----------------
    from slate_tpu.parallel import posv_distributed

    M = rng.standard_normal((m, m)).astype(np.float32)
    spdh = M @ M.T + m * np.eye(m, dtype=np.float32)
    Bh = rng.standard_normal((m, 4)).astype(np.float32)
    X = posv_distributed(jnp.asarray(spdh), jnp.asarray(Bh), grid, nb=8)
    Xref = np.linalg.solve(spdh, Bh)
    # verify this process's addressable shards only (a full np.asarray would
    # need a cross-process gather)
    for shard in X.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, Xref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-3)
    print(f"worker {pid}: posv OK", flush=True)

    # --- 4) tournament-pivoted LU spanning the process boundary -------------
    from slate_tpu.parallel import gesv_distributed

    G = rng.standard_normal((m, m)).astype(np.float32) + m * np.eye(
        m, dtype=np.float32)
    Xg, info = gesv_distributed(jnp.asarray(G), jnp.asarray(Bh), grid, nb=8)
    assert int(np.asarray(info.addressable_shards[0].data)) == 0
    Xgref = np.linalg.solve(G, Bh)
    for shard in Xg.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, Xgref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-3)
    print(f"worker {pid}: gesv OK", flush=True)

    # --- 5) explicit shard_map rank-k update (herk panel broadcast) ---------
    from slate_tpu.parallel import herk_distributed

    Ah = rng.standard_normal((m, 8)).astype(np.float32)
    Ch = rng.standard_normal((m, m)).astype(np.float32)
    Hk = herk_distributed(1.0, jnp.asarray(Ah), 0.5, jnp.asarray(Ch), grid)
    href = np.where(np.tril(np.ones((m, m), bool)),
                    Ah @ Ah.T + 0.5 * Ch, Ch)
    for shard in Hk.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, href[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-3)
    print(f"worker {pid}: herk OK", flush=True)

    # --- 6) round-3 stragglers across the process boundary: compact-band
    # Cholesky and CA-Aasen (their window psums / tournament all-gathers ride
    # the same flattened mesh axis pair)
    from slate_tpu.parallel import (dense_to_band_lower, hesv_distributed,
                                    pbsv_distributed)

    kd = 3
    Abd = np.zeros((m, m), np.float32)
    for j in range(1, kd + 1):
        v = rng.standard_normal(m - j).astype(np.float32)
        Abd += np.diag(v, j) + np.diag(v, -j)
    Abd += np.diag(np.abs(rng.standard_normal(m)).astype(np.float32)
                   + 4 * kd)
    Ab = dense_to_band_lower(jnp.asarray(np.tril(Abd)), kd)
    Xb, infob = pbsv_distributed(Ab, jnp.asarray(Bh), grid, kd, nb=8)
    Xbref = np.linalg.solve(Abd, Bh)
    for shard in Xb.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, Xbref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-3)
    print(f"worker {pid}: pbsv OK", flush=True)

    Hm = rng.standard_normal((m, m)).astype(np.float32)
    Hm = (Hm + Hm.T) / 2
    Xh, infoh = hesv_distributed(jnp.asarray(Hm), jnp.asarray(Bh), grid, nb=8)
    Xhref = np.linalg.solve(Hm, Bh)
    for shard in Xh.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, Xhref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]], atol=1e-2)
    print(f"worker {pid}: hesv OK", flush=True)

    # --- 7) round-4: distributed RBT solve (sharded butterfly + nopiv LU +
    # IR — its psums/trsm partitions are process-count agnostic like the
    # rest) across the two-process boundary
    from slate_tpu.parallel import gesv_rbt_distributed

    Gm = rng.standard_normal((m, m)).astype(np.float32)
    Xr, infor, _ = gesv_rbt_distributed(jnp.asarray(Gm), jnp.asarray(Bh),
                                        grid, depth=2, nb=8)
    Xrref = np.linalg.solve(Gm, Bh)
    for shard in Xr.addressable_shards:
        r0, c0 = (sl.start or 0 for sl in shard.index)
        blk = np.asarray(shard.data)
        np.testing.assert_allclose(
            blk, Xrref[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]],
            atol=1e-2)
    print(f"worker {pid}: rbt OK", flush=True)

    # --- 8) round-5: segment-parallel bulge chase — its per-round boundary
    # deltas and crossing-reflector ppermutes ride the flattened mesh axis,
    # so between devices 3 and 4 they cross the PROCESS boundary every round
    from slate_tpu.parallel import hb2st_chase_distributed
    from slate_tpu.linalg.eig import _hb2st_chase_pipelined

    nc, bc = 48, 2
    Mc = rng.standard_normal((nc, nc)).astype(np.float32)
    symc = (Mc + Mc.T) / 2
    iic = np.arange(nc)
    bandc = jnp.asarray(np.where(np.abs(iic[:, None] - iic[None, :]) <= bc,
                                 symc, 0))
    d_ref, e_ref, _, _ = _hb2st_chase_pipelined(bandc, bc)   # local replay
    dd, ee, _, _ = hb2st_chase_distributed(bandc, bc, grid)
    d_ref_np, e_ref_np = np.asarray(d_ref), np.asarray(e_ref)
    for shard in dd.addressable_shards:
        (sl,) = shard.index
        np.testing.assert_allclose(np.asarray(shard.data), d_ref_np[sl],
                                   atol=1e-4)
    for shard in ee.addressable_shards:
        (sl,) = shard.index
        np.testing.assert_allclose(np.asarray(shard.data), e_ref_np[sl],
                                   atol=1e-4)
    print(f"worker {pid}: chase OK", flush=True)

    jax.distributed.shutdown()
    print(f"worker {pid}: OK", flush=True)


def main() -> int:
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = []
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for pid in range(NPROC):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             coord, str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    deadline = time.time() + 600
    rc = 0
    outs = []
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=max(10, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            out = "(timeout)"
        if p.returncode != 0:
            rc = 1
        outs.append(out)
        print(f"--- worker {i} (rc={p.returncode}) ---\n{out}")
    # some jaxlib builds ship no multiprocess support for the CPU backend at
    # all (collectives raise INVALID_ARGUMENT at the first cross-process op).
    # That is an environment limitation, not a regression in this tree —
    # report an honest SKIP instead of a false FAIL so the single-process
    # 8-device tier (which covers the same SPMD code path) stays the gate.
    if rc != 0 and any("Multiprocess computations aren't implemented on the "
                       "CPU backend" in o for o in outs):
        print("MULTIPROCESS SKIP (jaxlib CPU backend lacks multiprocess "
              "collectives)")
        return 0
    print("MULTIPROCESS", "PASS" if rc == 0 else "FAIL")
    return rc


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2], int(sys.argv[3]))
    else:
        sys.exit(main())
