#!/usr/bin/env python
"""Sweep driver over the routine tester (≅ test/run_tests.py, 828 lines: size
classes --quick/--xsmall/--small/--medium/--large, shape filters, per-routine
timeout, JUnit XML for CI).

Examples::

    python tools/run_tests.py --quick
    python tools/run_tests.py --small --categories blas3,cholesky --xml out.xml
    python tools/run_tests.py --medium --routines gemm,posv --type s,c --ref
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import xml.etree.ElementTree as ET

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The tester must not land on the TPU tunnel: the ambient environment PINS
# JAX_PLATFORMS to the axon plugin, so setdefault() is not a defense — force
# CPU unless the caller explicitly opts into a platform via
# SLATE_TESTER_PLATFORM (correctness sweeps are platform-agnostic; the bench
# path owns the TPU).
_plat = os.environ.get("SLATE_TESTER_PLATFORM") or "cpu"
if _plat == "cpu":
    # correctness sweeps never touch the single-session TPU tunnel; shared
    # defense with tests/conftest.py
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from force_cpu import force_cpu_backend

    # grid sweeps need enough virtual devices for the requested p x q
    _vd = None
    _argv = sys.argv[1:]
    for _i, _a in enumerate(_argv):
        _spec = (_a.split("=", 1)[1] if _a.startswith("--grid=")
                 else _argv[_i + 1] if _a == "--grid" and _i + 1 < len(_argv)
                 else None)
        if _spec:
            _p, _q = (int(x) for x in _spec.lower().split("x"))
            _vd = _p * _q
    force_cpu_backend(virtual_devices=_vd)
else:
    os.environ["JAX_PLATFORMS"] = _plat

from slate_tpu.testing import ROUTINES                          # noqa: E402
from slate_tpu.testing.driver import run_sweep                  # noqa: E402
from slate_tpu.testing.sweeper import parse_list                # noqa: E402

SIZE_CLASSES = {
    # dims per class (≅ run_tests.py size classes); nb chosen to exercise blocking
    "quick":  {"dims": [64, 96], "nb": [32], "nrhs": 4},
    "xsmall": {"dims": [128], "nb": [32, 64], "nrhs": 8},
    "small":  {"dims": [256], "nb": [64], "nrhs": 8},
    "medium": {"dims": [512, 768], "nb": [128], "nrhs": 16},
    "large":  {"dims": [1024, 2048], "nb": [256], "nrhs": 16},
    # BASELINE-direction scale row: constant-factor data beyond the pytest
    # pin (the virtual mesh measures constants, not speedup — PERF_CPU.md)
    "xlarge": {"dims": [4096], "nb": [256], "nrhs": 16},
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    for cls in SIZE_CLASSES:
        ap.add_argument(f"--{cls}", action="store_true")
    ap.add_argument("--routines", default=None, help="comma list (default: all)")
    ap.add_argument("--categories", default=None, help="comma list of categories")
    ap.add_argument("--type", default="s", help="s,d,c,z")
    ap.add_argument("--tall", action="store_true", help="tall shapes m = 2n")
    ap.add_argument("--wide", action="store_true", help="wide shapes n = 2m")
    ap.add_argument("--ref", action="store_true", help="time numpy reference too")
    ap.add_argument("--timers", action="store_true",
                    help="print per-phase timer maps under eig/svd rows (the "
                         "reference tester's --timer-level 2)")
    ap.add_argument("--metrics", nargs="?", const="metrics.json", default=None,
                    metavar="PATH",
                    help="dump the sweep's metrics.json (slate_tpu.obs "
                         "registry: spans, phase histograms, tester row "
                         "counters, robust events) — default ./metrics.json")
    ap.add_argument("--xml", default=None, help="write JUnit XML here")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grid", default=None, metavar="PxQ",
                    help="sweep the distributed drivers on a PxQ process grid "
                         "(virtual devices; the reference tester's p/q dims)")
    args = ap.parse_args(argv)

    cls = next((c for c in SIZE_CLASSES if getattr(args, c)), "quick")
    cfg = SIZE_CLASSES[cls]

    names = sorted(ROUTINES)
    if args.routines:
        names = [r for r in parse_list(args.routines) if r in ROUTINES]
    if args.categories:
        cats = set(parse_list(args.categories))
        names = [r for r in names if ROUTINES[r]["category"] in cats]

    dims = []
    for d in cfg["dims"]:
        m, n = d, d
        if args.tall:
            m = 2 * d
        elif args.wide:
            n = 2 * d
        dims.append((m, n, d))

    def progress(r):
        status = r.status if r.ok else f"** {r.status} **"
        err = r.error if r.error is not None else float("nan")
        gf = f"{r.gflops:8.1f}" if r.gflops is not None else "       -"
        tm = f"{r.time_s:8.4f}" if r.time_s is not None else "       -"
        extra = ""
        iters = (r.details or {}).get("ir_iters")
        if iters is not None:
            extra = f" iters={iters}"
        print(f"{r.routine:16s} {r.params.get('dtype')} "
              f"{r.params['m']:5d}x{r.params['n']:<5d} nb={r.params['nb']:<4d} "
              f"t={tm}s gf={gf} err={err:.2e} {status}{extra} {r.message}",
              flush=True)
        phases = (r.details or {}).get("phases")
        if args.timers and phases:
            # --timer-level-2 analogue: one indented line per phase, hottest
            # first (phase_report already ordered them)
            total = phases.get("total_s", 0.0)
            for k, v in phases.items():
                if k == "total_s":
                    continue
                print(f"    {k:<24s} {v['s']:9.4f}s {v['pct']:5.1f}%",
                      flush=True)
            print(f"    {'total':<24s} {total:9.4f}s", flush=True)

    t0 = time.time()
    grid = (tuple(int(x) for x in args.grid.lower().split("x"))
            if args.grid else None)
    results = run_sweep(names, dims, parse_list(args.type), cfg["nb"],
                        seed=args.seed, nrhs=cfg["nrhs"], ref=args.ref,
                        grid=grid, progress=progress)
    elapsed = time.time() - t0

    npass = sum(1 for r in results if r.status == "pass")
    nskip = sum(1 for r in results if r.status == "skipped")
    nfail = len(results) - npass - nskip
    print(f"\n[{cls}] {len(results)} tests: {npass} pass, {nfail} failed, "
          f"{nskip} skipped in {elapsed:.1f}s")

    if args.metrics:
        from slate_tpu import obs

        print(f"wrote {obs.export_metrics(args.metrics, source='tester')}")

    if args.xml:
        suite = ET.Element("testsuite", name=f"slate_tpu-{cls}",
                           tests=str(len(results)), failures=str(nfail),
                           skipped=str(nskip), time=f"{elapsed:.2f}")
        for r in results:
            p = r.params
            case = ET.SubElement(
                suite, "testcase",
                classname=f"slate_tpu.{ROUTINES[r.routine]['category']}",
                name=f"{r.routine}_{p.get('dtype')}_{p.get('m')}x{p.get('n')}"
                     f"_nb{p.get('nb')}",
                time=f"{r.time_s or 0:.4f}")
            if r.status == "skipped":
                ET.SubElement(case, "skipped", message=r.message)
            elif r.status != "pass":
                ET.SubElement(case, "failure", message=r.message or r.status)
        ET.ElementTree(suite).write(args.xml, encoding="unicode",
                                    xml_declaration=True)
        print(f"wrote {args.xml}")

    return 0 if nfail == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
