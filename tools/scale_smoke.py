#!/usr/bin/env python
"""CI scale-smoke: the multi-executor serving data path on CPU (ISSUE 17).

Four gates (the ci.yml ``scale-smoke`` step fails on any):

* **Scaling**: warm mixed-traffic throughput at N=2 executors is
  non-decreasing vs N=1 (same seed, same protocol — the pool must never
  cost throughput on the axis it exists to scale).
* **Divergence**: ZERO cross-executor divergence — controlled request
  groups (exact max-batch chunks, awaited per group so every pool size
  sees identical batch rounding) produce BIT-identical solutions at
  N=1 and N=2.
* **Overload parity**: the overload-survival contract holds unchanged at
  N=2 — zero interactive sheds, zero hung tickets, zero unexpected
  worker errors, full capacity retained.
* **Death drain**: chaos-killing 1 of 2 executors completes EVERY ticket
  (value or typed error, zero hung); the survivor keeps serving and
  admission capacity scales to 1/2.

Per-executor observability rides the same run: the ``executor``-labelled
execute/pad histograms, the depth gauge, and per-executor cache counters
must be present in the exported registry.  Artifacts:
``scale_metrics.json``.  Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from force_cpu import force_cpu_backend  # noqa: E402

force_cpu_backend()

# CI runners are noisy: the scaling gate tolerates a small regression band
# rather than demanding strict speedup from a 2-vCPU machine, but N=2 must
# never fall meaningfully below N=1
SCALE_FLOOR = 0.9
OVERLOAD_DURATION_S = 12.0


def _bit_identity_failures():
    """Serve three exact-max-batch groups per routine at N=1 and N=2 with
    identical chunking (await each group) and compare solutions bytewise."""
    import numpy as np

    from slate_tpu import serve
    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.queue import BucketPolicy

    def groups_for(routine):
        rng = np.random.default_rng(7)
        out = []
        for _ in range(3):
            reqs = []
            for _ in range(4):
                n = 8
                if routine == "gels":
                    a = rng.standard_normal((2 * n, n)).astype(np.float32)
                    b = rng.standard_normal((2 * n, 1)).astype(np.float32)
                    reqs.append((routine, a, b))
                    continue
                if routine == "posv":
                    g = rng.standard_normal((n, n)).astype(np.float32)
                    a = (g @ g.T + n * np.eye(n)).astype(np.float32)
                else:
                    a = rng.standard_normal((n, n)).astype(np.float32) \
                        + n * np.eye(n, dtype=np.float32)
                b = rng.standard_normal((n, 1)).astype(np.float32)
                reqs.append((routine, a, b))
            out.append(reqs)
        return out

    def run(executors, groups):
        policy = BucketPolicy(max_batch=4, batch_dims=(1, 4),
                              max_wait_ms=500.0)
        q = serve.ServeQueue(policy=policy, cache=ExecutableCache(),
                             executors=executors)
        try:
            solved = []
            for g in groups:
                ts = [q.submit(r, a, b) for r, a, b in g]
                solved.append([t.result(timeout=120.0) for t in ts])
            return solved
        finally:
            q.close()

    failures = []
    for routine in ("gesv", "posv", "gels"):
        groups = groups_for(routine)
        ref = run(1, groups)
        got = run(2, groups)
        for gi, (gr, gg) in enumerate(zip(ref, got)):
            for (xr, ir), (xg, ig) in zip(gr, gg):
                if int(ir) != 0 or int(ig) != 0:
                    failures.append(f"{routine} group {gi}: nonzero info "
                                    f"(N1={int(ir)}, N2={int(ig)})")
                elif np.asarray(xr).tobytes() != np.asarray(xg).tobytes():
                    failures.append(f"{routine} group {gi}: N=2 solution "
                                    "DIVERGES bytewise from N=1")
    return failures


def _death_drain_failures():
    """Kill executor 0 of 2 mid-stream: every ticket must resolve (zero
    hung), only the in-flight chunk may fail, the survivor keeps serving."""
    import numpy as np

    from slate_tpu import robust, serve
    from slate_tpu.core.exceptions import SlateError
    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.queue import BucketPolicy

    failures = []
    q = serve.ServeQueue(
        policy=BucketPolicy(max_batch=4, batch_dims=(1, 4), max_wait_ms=2.0),
        cache=ExecutableCache(), executors=2)
    rng = np.random.default_rng(11)
    try:
        with robust.FaultPlan([robust.FaultSpec(
                serve.SERVE_SITE, "worker_crash", executor=0)]):
            ts = []
            for _ in range(40):
                a = rng.standard_normal((8, 8)).astype(np.float32) \
                    + 8 * np.eye(8, dtype=np.float32)
                b = rng.standard_normal((8, 1)).astype(np.float32)
                ts.append(q.submit("gesv", a, b))
            ok = failed = hung = 0
            for t in ts:
                try:
                    _, info = t.result(timeout=60.0)
                    ok += 1 if int(info) == 0 else 0
                except SlateError as e:
                    if "worker thread died" in str(e):
                        failed += 1
                    else:
                        failures.append(f"unexpected typed error: {e}")
                except TimeoutError:
                    hung += 1
        if hung:
            failures.append(f"{hung} tickets HUNG after executor death")
        if not 1 <= failed <= 4:
            failures.append(f"{failed} tickets failed — expected only the "
                            "dying executor's in-flight chunk (1..4)")
        if ok != len(ts) - failed:
            failures.append(f"only {ok}/{len(ts) - failed} rerouted tickets "
                            "solved clean")
        if q.capacity_fraction() != 0.5:
            failures.append(f"pool capacity_fraction {q.capacity_fraction()}"
                            " != 0.5 after losing 1 of 2 executors")
        t = q.submit("gesv", 8 * np.eye(8, dtype=np.float32),
                     np.ones((8, 1), np.float32))
        _, info = t.result(timeout=60.0)
        if int(info) != 0 or t.executor != "ex1":
            failures.append("survivor executor not serving after the death "
                            f"(info={int(info)}, executor={t.executor!r})")
    finally:
        q.close()
    return failures


def main() -> int:
    from slate_tpu import obs, serve

    failures = []

    # -- scaling gate --------------------------------------------------------
    out = serve.run_scale_workload(executor_counts=(1, 2), num_requests=600,
                                   seed=0)
    sps = out["solves_per_sec"]
    if sps["2"] < SCALE_FLOOR * sps["1"]:
        failures.append(f"N=2 warm throughput {sps['2']:.1f} solves/s fell "
                        f"below {SCALE_FLOOR:.0%} of N=1 ({sps['1']:.1f})")
    for n, stats in out["runs"].items():
        if stats["misses_after_warmup"]:
            failures.append(f"N={n}: {stats['misses_after_warmup']} cache "
                            "misses in the measured pass — warmup must cover "
                            "every executor's cache")

    # -- divergence gate -----------------------------------------------------
    failures += _bit_identity_failures()

    # -- overload parity at N=2 ----------------------------------------------
    ostats = serve.run_overload_workload(duration_s=OVERLOAD_DURATION_S,
                                         seed=0, executors=2)
    if ostats["shed_by_lane"].get("interactive", 0):
        failures.append(f"{ostats['shed_by_lane']['interactive']} interactive"
                        " requests shed at N=2 — lane ladder broken by pool")
    if ostats["hung"]:
        failures.append(f"{ostats['hung']} tickets unresolved at N=2")
    if ostats["worker_failed"]:
        failures.append(f"{ostats['worker_failed']} unexpected worker "
                        "errors at N=2")
    if ostats["capacity_fraction_final"] != 1.0:
        failures.append("capacity fraction degraded without any executor "
                        f"death: {ostats['capacity_fraction_final']}")

    # -- death drain gate ----------------------------------------------------
    failures += _death_drain_failures()

    # -- per-executor observability ------------------------------------------
    doc = obs.metrics_doc(source="scale-smoke")
    try:
        obs.validate_metrics(doc)
    except ValueError as e:
        failures.append(f"metrics schema violation: {e}")
    by_name = {m["name"]: m for m in doc["metrics"]}
    for need in ("slate_serve_execute_seconds", "slate_serve_pad_seconds"):
        m = by_name.get(need)
        execs = {s["labels"].get("executor") for s in m["samples"]
                 if s["labels"].get("executor")} if m else set()
        if len(execs) < 2:
            failures.append(f"{need} lacks per-executor series "
                            f"(saw {sorted(execs)})")
    if "slate_serve_executor_depth" not in by_name:
        failures.append("slate_serve_executor_depth gauge missing")
    if "slate_serve_requeued_chunks_total" not in by_name:
        failures.append("slate_serve_requeued_chunks_total missing — the "
                        "death drain did not requeue through the counter")
    obs.export_metrics("scale_metrics.json", source="scale-smoke")

    print(json.dumps({
        "ok": not failures,
        "solves_per_sec": sps,
        "n2_over_n1": round(sps["2"] / max(sps["1"], 1e-9), 3),
        "overload_n2": {
            "admitted": ostats["admitted"], "ok": ostats["ok"],
            "shed_by_lane": ostats["shed_by_lane"],
            "hung": ostats["hung"],
            "recalibrations": ostats["recalibrations"],
        },
        "artifacts": ["scale_metrics.json"],
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
