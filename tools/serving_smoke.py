#!/usr/bin/env python
"""CI serving-smoke: a short mixed workload through the serving queue on CPU,
run under the runtime-telemetry tier.

Gates (the ci.yml ``serving-smoke`` step fails on any):

* the workload runs end-to-end (every request info == 0, finite results),
* solves/sec > 0 and p50/p99 latency are recorded,
* ZERO executable-cache misses after warm-up (the compile-count property —
  a silent recompile in the serving path fails CI here in CPU seconds),
* the run's metrics.json validates against the shared schema and carries
  the serving counters (requests, batches, occupancy, cache hits) AND the
  stage histograms (queue-wait / execute / pad),
* the sampler's ``metrics_timeseries.json`` validates against
  ``slate_tpu.timeseries/v1`` and carries >= 2 traffic windows,
* every declared serve SLO evaluates to an EXPLICIT verdict (ok / warning /
  breach — ``no_data`` on a routine that served traffic fails), and none
  reads ``breach``,
* every sampled request's spans are stitchable from the chrome-trace by its
  ticket's trace id (submit, queue-wait, execute, resolve at minimum).

Artifacts written for CI upload: ``metrics_timeseries.json``,
``OBS_REPORT.md``, ``serving_metrics.json``, ``serving_trace.json``,
``flight_records.json``.  Prints one JSON line with the numbers so the CI
log doubles as a record.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from force_cpu import force_cpu_backend  # noqa: E402

force_cpu_backend()

NUM_REQUESTS = 300
STITCH_SAMPLE = 8          # tickets spot-checked for trace stitchability
REQUIRED_STAGES = {"serve.submit", "serve.queue_wait", "serve.execute",
                   "serve.resolve"}


def main() -> int:
    from slate_tpu import obs, serve
    from slate_tpu.serve.queue import BucketPolicy
    from slate_tpu.utils import trace

    import obs_report

    # compact policy: enough bucket diversity to exercise mixed packing,
    # small enough that warm-up stays in CI seconds
    policy = BucketPolicy(dims=(16, 32, 64), nrhs_dims=(2,),
                          batch_dims=(1, 8, 32), max_batch=32,
                          max_wait_ms=5.0)
    flight = serve.FlightRecorder(auto_dump_path="flight_records.json")
    sampler = obs.TimeSeriesSampler(interval_s=0.25)
    # the smoke submits all requests in one burst, so submit-to-result
    # latency is dominated by standing in line behind the whole backlog —
    # the latency objective is sized for that burst (plus slow CI runners),
    # not for steady-state serving
    monitor = obs.SLOMonitor(
        obs.default_serve_slos(p99_latency_s=30.0, warmup_windows=0,
                               windows=10_000), sampler)

    def after_warmup(q):
        # telemetry tier, armed between warm-up and the measured pass: the
        # sampler baseline lands AFTER warm-up (so the hit-rate SLO sees
        # steady-state traffic, not the warm-up compiles) and tracing turns
        # on so the stage spans land in the chrome-trace
        trace.on()
        sampler.start()
        q.attach_slo(monitor)

    stats = serve.run_mixed_workload(
        num_requests=NUM_REQUESTS, seed=0, policy=policy,
        dims=(8, 13, 24, 40, 60), use_queue=True, warm=True, check=False,
        flight=flight, return_tickets=True, after_warmup=after_warmup)
    tickets = stats["tickets"]
    sampler.stop()          # takes the final window
    trace_path = trace.finish("serving_trace.json")
    trace.off()

    failures = []
    if stats["bad"]:
        failures.append(f"{stats['bad']}/{stats['requests']} requests "
                        "returned nonzero info or non-finite results")
    p50_ms, p99_ms = stats["p50_ms"], stats["p99_ms"]
    if p50_ms is None or p99_ms is None:
        failures.append("p50/p99 latency not recorded")
    if not stats["solves_per_sec"] > 0:
        failures.append(f"solves/sec not positive: {stats['solves_per_sec']}")
    if stats["misses_after_warmup"] != 0:
        failures.append(f"{stats['misses_after_warmup']} cache misses after "
                        "warm-up (silent recompiles in the serving path)")
    if stats["distinct_buckets"] < 4:
        failures.append(f"only {stats['distinct_buckets']} shape buckets "
                        "exercised (need >= 4)")

    # -- metrics.json: schema + serving counters + stage histograms ---------
    doc = obs.metrics_doc(source="serving-smoke")
    try:
        obs.validate_metrics(doc)
    except ValueError as e:
        failures.append(f"metrics.json schema violation: {e}")
    names = {m["name"] for m in doc["metrics"]}
    for need in ("slate_serve_requests_total", "slate_serve_batches_total",
                 "slate_serve_batch_occupancy",
                 "slate_serve_cache_hits_total",
                 "slate_serve_latency_seconds",
                 "slate_serve_queue_wait_seconds",
                 "slate_serve_execute_seconds",
                 "slate_serve_pad_seconds"):
        if need not in names:
            failures.append(f"metric {need} missing from the registry")
    obs.export_metrics("serving_metrics.json", source="serving-smoke")

    # -- timeseries + SLO verdicts ------------------------------------------
    verdicts = monitor.evaluate()
    ts_path = sampler.export("metrics_timeseries.json",
                             source="serving-smoke",
                             slos=[v.to_dict() for v in verdicts])
    ts_doc = json.load(open(ts_path))
    try:
        obs.validate_timeseries(ts_doc)
    except ValueError as e:
        failures.append(f"metrics_timeseries.json schema violation: {e}")
    # >= 1 is deterministic (all served traffic lands in SOME window's
    # deltas); >= 2 would flake whenever a fast runner drains the warm
    # workload inside one sampler tick.  Multi-window rate math is pinned
    # by tests/test_obs.py with explicit timestamps instead.
    traffic_windows = [
        w for w in ts_doc["windows"]
        if any(e["name"].startswith("slate_serve_")
               for e in w["counters"] + w["histograms"])]
    if not traffic_windows:
        failures.append("no sampled window carries serving traffic")
    served = set(stats["routines"])
    for v in verdicts:
        routine = v.name.split("_")[0]
        has_traffic = v.kind != "latency" or routine in served
        if has_traffic and v.verdict == "no_data":
            failures.append(f"SLO {v.name}: no verdict despite traffic")
        if v.verdict == "breach":
            failures.append(f"SLO {v.name}: BREACH ({v.detail})")
    if not verdicts:
        failures.append("no SLO verdicts evaluated")

    # -- trace stitchability ------------------------------------------------
    stitched = 0
    if trace_path is None:
        failures.append("no chrome-trace written")
    else:
        events = json.load(open(trace_path))["traceEvents"]
        by_id = {}
        for e in events:
            tid = e.get("args", {}).get("trace_id")
            if tid is not None:
                by_id.setdefault(tid, set()).add(e["name"])
        step = max(len(tickets) // STITCH_SAMPLE, 1)
        sample = tickets[::step][:STITCH_SAMPLE]
        for t in sample:
            have = by_id.get(t.trace_id, set())
            if REQUIRED_STAGES <= have:
                stitched += 1
            else:
                failures.append(
                    f"ticket {t.trace_id}: spans not stitchable "
                    f"(missing {sorted(REQUIRED_STAGES - have)})")

    # -- flight recorder + report -------------------------------------------
    flight_path = flight.dump("flight_records.json")
    if len(flight.records()) < min(NUM_REQUESTS, flight.capacity):
        failures.append(f"flight recorder holds {len(flight.records())} "
                        "records, expected one per served request")
    report = obs_report.render_report(ts_doc, doc,
                                      json.load(open(flight_path)))
    with open("OBS_REPORT.md", "w") as f:
        f.write(report)
    for need in ("## SLO verdicts", "## Per-routine stage-latency",
                 "queue-wait p50/p99"):
        if need not in report:
            failures.append(f"OBS_REPORT.md missing section: {need!r}")

    print(json.dumps({
        "ok": not failures,
        "solves_per_sec": stats["solves_per_sec"],
        "p50_ms": p50_ms, "p99_ms": p99_ms,
        "requests": stats["requests"],
        "distinct_buckets": stats["distinct_buckets"],
        "cache": stats["cache"],
        "misses_after_warmup": stats["misses_after_warmup"],
        "warmup_s": (stats["warmup"] or {}).get("seconds"),
        "windows": len(ts_doc["windows"]),
        "slo": {v.name: v.verdict for v in verdicts},
        "stitched_tickets": stitched,
        "flight_records": len(flight.records()),
        "artifacts": ["metrics_timeseries.json", "OBS_REPORT.md",
                      "serving_metrics.json", "serving_trace.json",
                      "flight_records.json"],
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
