#!/usr/bin/env python
"""CI serving-smoke: a short mixed workload through the serving queue on CPU.

Gates (the ci.yml ``serving-smoke`` step fails on any):

* the workload runs end-to-end (every request info == 0, finite results),
* solves/sec > 0 and p50/p99 latency are recorded,
* ZERO executable-cache misses after warm-up (the compile-count property —
  a silent recompile in the serving path fails CI here in CPU seconds),
* the run's metrics.json validates against the shared schema and carries
  the serving counters (requests, batches, occupancy, cache hits).

Prints one JSON line with the numbers so the CI log doubles as a record.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from force_cpu import force_cpu_backend  # noqa: E402

force_cpu_backend()


def main() -> int:
    from slate_tpu import obs, serve
    from slate_tpu.serve.queue import BucketPolicy

    # compact policy: enough bucket diversity to exercise mixed packing,
    # small enough that warm-up stays in CI seconds
    policy = BucketPolicy(dims=(16, 32, 64), nrhs_dims=(2,),
                          batch_dims=(1, 8, 32), max_batch=32,
                          max_wait_ms=5.0)
    stats = serve.run_mixed_workload(
        num_requests=300, seed=0, policy=policy,
        dims=(8, 13, 24, 40, 60), use_queue=True, warm=True, check=True)

    failures = []
    if not stats["solves_per_sec"] > 0:
        failures.append(f"solves/sec not positive: {stats['solves_per_sec']}")
    if stats["p50_ms"] is None or stats["p99_ms"] is None:
        failures.append("p50/p99 latency not recorded")
    if stats["misses_after_warmup"] != 0:
        failures.append(f"{stats['misses_after_warmup']} cache misses after "
                        "warm-up (silent recompiles in the serving path)")
    if stats["distinct_buckets"] < 4:
        failures.append(f"only {stats['distinct_buckets']} shape buckets "
                        "exercised (need >= 4)")

    doc = obs.metrics_doc(source="serving-smoke")
    try:
        obs.validate_metrics(doc)
    except ValueError as e:
        failures.append(f"metrics.json schema violation: {e}")
    names = {m["name"] for m in doc["metrics"]}
    for need in ("slate_serve_requests_total", "slate_serve_batches_total",
                 "slate_serve_batch_occupancy",
                 "slate_serve_cache_hits_total",
                 "slate_serve_latency_seconds"):
        if need not in names:
            failures.append(f"metric {need} missing from the registry")

    print(json.dumps({
        "ok": not failures,
        "solves_per_sec": stats["solves_per_sec"],
        "p50_ms": stats["p50_ms"], "p99_ms": stats["p99_ms"],
        "requests": stats["requests"],
        "distinct_buckets": stats["distinct_buckets"],
        "cache": stats["cache"],
        "misses_after_warmup": stats["misses_after_warmup"],
        "warmup_s": (stats["warmup"] or {}).get("seconds"),
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
