"""Capture a jax.profiler trace of the potrf bench body on the real chip
(VERDICT r3 #2: "profile on chip (jax.profiler trace in-repo)").

Writes a TensorBoard-loadable trace to ./tpu_trace/potrf/ — the artifact
that shows where the 0.93x goes (panel chol vs trsm vs trailing gemm vs
dispatch gaps).  Single tunnel user; run only via tools/tpu_watch.sh after
the bench captures.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no TPU; skipping profile capture")
        return 1
    import slate_tpu

    n = int(os.environ.get("PROFILE_POTRF_N", 16384))
    nb = int(os.environ.get("BENCH_POTRF_NB", 2048))
    key = jax.random.PRNGKey(0)
    m = jax.random.normal(key, (n, n), dtype=jnp.float32) / jnp.sqrt(
        jnp.asarray(n, jnp.float32))
    a = jnp.matmul(m, m.T, precision=lax.Precision.HIGHEST) + 2.0 * jnp.eye(
        n, dtype=jnp.float32)
    opts = {"target": "tiled", "block_size": nb}

    def run(x):
        return slate_tpu.potrf(x, opts=opts)[0]

    # warm/compile outside the trace
    float(run(a).ravel()[0])
    out_dir = os.path.join(REPO, "tpu_trace", "potrf")
    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(out_dir):
        r = run(a + 1e-6 * jnp.eye(n, dtype=a.dtype))
        float(r.ravel()[0])
    print(f"trace captured in {time.perf_counter() - t0:.2f}s -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
