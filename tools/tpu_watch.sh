#!/bin/bash
# Tunnel-recovery watcher: probe the TPU tunnel at a low duty cycle; the
# moment it answers, run the bench configs that still need fresh hardware
# numbers (recorded into BENCH_LKG.json by bench.py itself).  Single user of
# the tunnel by design — nothing else should touch it while this runs.
cd "$(dirname "$0")/.."
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu'" 2>/dev/null; then
    echo "[tpu_watch] tunnel healthy at attempt $i ($(date -u +%H:%M:%S)); running bench"
    BENCH_DEADLINE_SEC=5400 timeout 5700 python bench.py --only getrf,svd,heev,potrf 2>&1 | tail -2
    echo "[tpu_watch] bench done ($(date -u +%H:%M:%S))"
    exit 0
  fi
  sleep 150
done
echo "[tpu_watch] gave up after 200 attempts"
exit 1
