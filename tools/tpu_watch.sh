#!/bin/bash
# Tunnel-recovery watcher: probe the TPU tunnel at a low duty cycle; the
# moment it answers, capture the outstanding bench configs into
# BENCH_LKG.json in VERDICT-r3 priority order, then the block-size sweeps.
# Single tunnel user by design.  Each bench.py invocation is a separate
# parent (fresh probe) so one wedged child cannot strand the later groups.
cd "$(dirname "$0")/.."
for i in $(seq 1 400); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu'" 2>/dev/null; then
    echo "[tpu_watch] tunnel healthy at attempt $i ($(date -u +%H:%M:%S))"
    # (a) the two-rounds-overdue getrf two-level CALU number
    BENCH_DEADLINE_SEC=1800 timeout 2000 python bench.py --only getrf 2>&1 | tail -1
    echo "[tpu_watch] getrf done ($(date -u +%H:%M:%S))"
    # (b) heev/svd at the BASELINE-scale configs
    BENCH_DEADLINE_SEC=3000 timeout 3200 python bench.py --only heev,svd 2>&1 | tail -1
    echo "[tpu_watch] heev/svd done ($(date -u +%H:%M:%S))"
    # (c) the round-4 additions: lookahead potrf, f64 story, two-stage timing
    BENCH_DEADLINE_SEC=7000 timeout 7300 python bench.py --only potrf_la,f64gemm,gesvir,heev2s,svd2s 2>&1 | tail -1
    echo "[tpu_watch] r4 configs done ($(date -u +%H:%M:%S))"
    # (d) refresh the five round-3 captures
    BENCH_DEADLINE_SEC=2400 timeout 2700 python bench.py --only gemm,norm,potrf,gels 2>&1 | tail -1
    echo "[tpu_watch] refresh done ($(date -u +%H:%M:%S)); sweeps"
    for cfg in "2048 512" "1024 256" "2048 128"; do
      set -- $cfg
      echo "[sweep] getrf nb=$1 ib=$2"
      BENCH_GETRF_NB=$1 BENCH_GETRF_IB=$2 timeout 1500 \
        python bench.py --child getrf 2>&1 | tail -1
    done
    for nb in 1024 4096; do
      echo "[sweep] potrf nb=$nb"
      BENCH_POTRF_NB=$nb timeout 1200 \
        python bench.py --child potrf 2>&1 | tail -1
    done
    echo "[sweep] potrf inverse-apply panel"
    BENCH_POTRF_INVTRSM=1 timeout 1200 \
      python bench.py --child potrf 2>&1 | tail -1
    echo "[sweep] norm via plain XLA reduction (A/B vs Pallas)"
    BENCH_NORM_IMPL=xla timeout 1200 \
      python bench.py --child norm 2>&1 | tail -1
    for nb in 1024 4096; do
      echo "[sweep] potrf_la nb=$nb"
      BENCH_POTRF_LA_NB=$nb timeout 1200 \
        python bench.py --child potrf_la 2>&1 | tail -1
    done
    echo "[profile] potrf jax.profiler trace"
    timeout 1200 python tools/tpu_profile_potrf.py 2>&1 | tail -2
    echo "[tpu_watch] all done ($(date -u +%H:%M:%S))"
    exit 0
  fi
  sleep 150
done
echo "[tpu_watch] gave up after 400 attempts"
exit 1
