#!/bin/bash
# Tunnel-recovery watcher: probe the TPU tunnel at a low duty cycle; the
# moment it answers, (1) capture the outstanding bench configs into
# BENCH_LKG.json, then (2) run the VERDICT-requested block-size sweeps for
# getrf/potrf, logging each child's JSON line.  Single tunnel user by design.
cd "$(dirname "$0")/.."
for i in $(seq 1 400); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu'" 2>/dev/null; then
    echo "[tpu_watch] tunnel healthy at attempt $i ($(date -u +%H:%M:%S)); bench"
    BENCH_DEADLINE_SEC=5400 timeout 5700 python bench.py --only getrf,svd,heev,potrf 2>&1 | tail -2
    echo "[tpu_watch] main bench done ($(date -u +%H:%M:%S)); sweeps"
    for cfg in "2048 512" "1024 256" "2048 128"; do
      set -- $cfg
      echo "[sweep] getrf nb=$1 ib=$2"
      BENCH_GETRF_NB=$1 BENCH_GETRF_IB=$2 timeout 1500 \
        python bench.py --child getrf 2>&1 | tail -1
    done
    for nb in 1024 4096; do
      echo "[sweep] potrf nb=$nb"
      BENCH_POTRF_NB=$nb timeout 1200 \
        python bench.py --child potrf 2>&1 | tail -1
    done
    echo "[tpu_watch] all done ($(date -u +%H:%M:%S))"
    exit 0
  fi
  sleep 150
done
echo "[tpu_watch] gave up after 400 attempts"
exit 1
