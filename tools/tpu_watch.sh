#!/bin/bash
# Tunnel-recovery watcher v3 (round 6): single tunnel owner; captures the
# outstanding bench configs into BENCH_LKG.json in ISSUE-r6 priority order —
# staged-but-unmeasured hot-path work first (lane-aligned norms; potrf Tiled
# vs lookahead pipeline in ONE window; getrf tournament-vs-pp A/B in ONE
# window), then the coverage/refresh tail.
#
# Changes vs v1 after the 09:20 wedge forensics:
# - every group (and every sweep child) is gated by its OWN probe, so a
#   tunnel that dies mid-round makes the watcher WAIT instead of burning
#   the remaining groups as CPU-fallback rows (today's r4 group lost 19 min
#   that way);
# - bench children are budget-aware now (BENCH_CHILD_BUDGET_SEC): they emit
#   a truncated measurement and exit instead of being SIGKILLed mid-RPC —
#   the kill is the documented wedge trigger, and the heev/svd group doing
#   exactly that at 08:35-09:20 is what took the tunnel down;
# - cheap/robust configs first (norm, potrf and its closers), the
#   minutes-per-call eig/SVD configs last;
# - resumable: completed steps are recorded in .tpu_watch_done so a watcher
#   restart (session handoff) does not redo captures.
cd "$(dirname "$0")/.."
STATE=.tpu_watch_done

log() { echo "[tpu_watch] $* ($(date -u +%H:%M:%S))"; }
probe_ok() {
  timeout 90 python -c \
    "import jax; d=jax.devices(); assert d[0].platform != 'cpu'" 2>/dev/null
}
wait_tunnel() {  # $1 = max probes, 150 s apart
  local i
  for i in $(seq 1 "$1"); do
    probe_ok && return 0
    sleep 150
  done
  return 1
}
done_step() { grep -qxF "$1" "$STATE" 2>/dev/null; }
mark_done() { echo "$1" >> "$STATE"; }

run_group() {  # $1 name, $2 configs, $3 deadline, $4 timeout
  done_step "$1" && return 0
  wait_tunnel 40 || { log "tunnel never opened for $1"; return 1; }
  log "start $1 ($2)"
  BENCH_DEADLINE_SEC=$3 timeout "$4" python bench.py --only "$2" 2>&1 | tail -1
  log "done $1"
  mark_done "$1"
}

run_child() {  # $1 step name, $2 timeout, $3 config, rest = env pairs
  done_step "$1" && return 0
  probe_ok || { log "tunnel down; skip $1 this pass"; return 1; }
  log "start $1"
  local step=$1 to=$2 cfg=$3; shift 3
  env "$@" BENCH_CHILD_BUDGET_SEC=$((to - 120)) timeout "$to" \
    python bench.py --child "$cfg" 2>&1 | tail -1
  mark_done "$step"
}

# one outer loop so a group whose tunnel-wait expired gets another chance
for pass in 1 2 3; do
  log "pass $pass"
  # (a) STAGED-FIRST (ISSUE r6): the two decisions that need same-window
  #     evidence land before anything else burns tunnel budget —
  #     * norm: the lane-aligned (8,128) Pallas rewrite vs its 0.255x LKG;
  #     * potrf vs potrf_la: Tiled vs the explicit lookahead pipeline at the
  #       SAME n=16384 job in the SAME window (potrf.cc:136-177 decision)
  run_group g_norm_potrf_la "norm,potrf,potrf_la" 2700 2900
  run_child s_norm_xla 900 norm BENCH_NORM_IMPL=xla
  # (b) the getrf regression A/B: tournament vs pp panel back-to-back in one
  #     window (bisection arm 2 — BENCH_NOTES.md round-6 section)
  run_group g_getrf_ab "getrf,getrf_pp" 3000 3200
  # (c) potrf closers
  run_child s_potrf_nb1024 900 potrf BENCH_POTRF_NB=1024
  run_child s_potrf_nb4096 900 potrf BENCH_POTRF_NB=4096
  run_child s_potrf_inv 900 potrf BENCH_POTRF_INVTRSM=1
  run_child s_potrf_la_nb1024 1000 potrf_la BENCH_POTRF_LA_NB=1024
  # (d) round-4 additions that have never touched the chip
  run_group g_f64_ir "f64gemm,gesvir" 1800 2000
  # (e) two-stage pipelines: a quick n=4096 capture first (lands evidence
  #     in a short tunnel window), then the n=8192 configs with phase splits
  run_child s_heev2s_n4096 1200 heev2s BENCH_HEEV2S_N=4096
  run_child s_svd2s_n4096 1200 svd2s BENCH_SVD2S_N=4096
  run_group g_twostage "heev2s,svd2s" 4000 4300
  # (f) BASELINE-scale heev/svd (budget-truncating children land a number)
  run_group g_heev_svd "heev,svd" 3200 3400
  # (g) getrf blocking sweeps (reconnect with the round-2 6.8 TF/s evidence)
  run_child s_getrf_nb2048_ib512 1500 getrf BENCH_GETRF_NB=2048 BENCH_GETRF_IB=512
  run_child s_getrf_nb2048_ib128 1500 getrf BENCH_GETRF_NB=2048 BENCH_GETRF_IB=128
  run_child s_getrf_nb1024_ib256 1500 getrf BENCH_GETRF_NB=1024 BENCH_GETRF_IB=256
  run_child s_getrf_nb4096_ib512 1500 getrf BENCH_GETRF_NB=4096 BENCH_GETRF_IB=512
  # (h) refresh the round-3 captures that already have good cached numbers
  run_group g_refresh "gemm,gels" 1500 1700
  # (g) potrf profile trace for the lookahead analysis
  if ! done_step s_profile && probe_ok; then
    log "start s_profile"
    timeout 1200 python tools/tpu_profile_potrf.py 2>&1 | tail -2
    mark_done s_profile
  fi
  if [ "$(grep -c . "$STATE" 2>/dev/null || echo 0)" -ge 18 ]; then
    log "all 18 steps complete"
    exit 0
  fi
done
log "passes exhausted; $(grep -c . "$STATE" 2>/dev/null || echo 0)/18 steps done"
