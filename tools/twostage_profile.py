"""Phase profile of the distributed two-stage eigensolver on the virtual
mesh (VERDICT r3 #4: "attack the distributed two-stage constants — profile
where it goes: the chase? the merge secular iters? collective
serialization?").

Times each phase of heev_distributed(n, 2x4 virtual CPU mesh) separately
with block_until_ready fences.  Virtual-mesh wall clock can NEVER show
distributed speedup (8 'devices' share the same cores — round-3 memory
note); what it CAN show is the phase SPLIT, which is what directs the fix.

Usage: python tools/twostage_profile.py [n]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from force_cpu import force_cpu_backend

force_cpu_backend(virtual_devices=8)

import jax
import jax.numpy as jnp
import numpy as np


from bench_util import fence  # one fence definition across the tools


def timed(label, fn, *args, **kw):
    t0 = time.perf_counter()
    out = fence(fn(*args, **kw))
    t1 = time.perf_counter()   # includes compile on first call — rerun below
    t2 = time.perf_counter()
    out = fence(fn(*args, **kw))
    t3 = time.perf_counter()
    print(f"{label:28s} first={t1 - t0:8.2f}s  steady={t3 - t2:8.2f}s",
          flush=True)
    return out, t3 - t2


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    from slate_tpu.parallel import ProcessGrid
    from slate_tpu.parallel.eig_dist import (he2hb_distributed,
                                             unmtr_he2hb_distributed)
    from slate_tpu.parallel.summa import gemm_padded
    from slate_tpu.linalg.eig import hb2st, sterf
    from slate_tpu.linalg.stedc import stedc

    grid = ProcessGrid(2, 4)
    rng = np.random.default_rng(0)
    m = rng.standard_normal((n, n)).astype(np.float64)
    a = jnp.asarray((m + m.T) / 2)
    nb = max(2, min(64, -(-n // (4 * 8))))
    print(f"n={n} nb={nb} grid=2x4 (virtual)", flush=True)

    (band, Vs, Ts), t1 = timed("stage1 he2hb_distributed",
                               lambda x: he2hb_distributed(x, grid, nb=nb), a)
    band_r = jax.device_put(band, grid.replicated())

    (d, e, Q2), t2 = timed("stage2 hb2st (+vectors)",
                           lambda b: hb2st(b, kd=nb, want_vectors=True,
                                           pipeline=False), band_r)
    _, t2p = timed("stage2 hb2st (pipelined)",
                   lambda b: hb2st(b, kd=nb, want_vectors=True,
                                   pipeline=True), band_r)
    _, t3v = timed("sterf (values only)", sterf, d, e)
    (lam, Zt), t3 = timed("stedc (dist merges)",
                          lambda dd, ee: stedc(dd, ee, grid=grid), d, e)
    (Z,), t4 = timed("back-transform Q2@Zt",
                     lambda q, z: (gemm_padded(q, z.astype(q.dtype), grid),),
                     Q2, Zt)
    (Zf,), t5 = timed("back-transform unmtr",
                      lambda v, t, z: (unmtr_he2hb_distributed(
                          v, t, z, grid, conj_q=False),), Vs, Ts, Z)
    total = t1 + t2 + t3 + t4 + t5
    print(f"\nsteady-state total (vectors, DC): {total:.2f}s")
    for label, t in [("stage1", t1), ("chase", t2), ("stedc", t3),
                     ("Q2 gemm", t4), ("unmtr", t5)]:
        print(f"  {label:10s} {t:8.2f}s  {100 * t / total:5.1f}%")
    print(f"  (pipelined chase alternative: {t2p:.2f}s; "
          f"values-only sterf: {t3v:.2f}s)")

    # correctness spot check
    T = np.asarray(a)
    ref = np.linalg.eigvalsh(T)
    err = np.max(np.abs(np.sort(np.asarray(lam)) - ref)) / np.max(np.abs(ref))
    print(f"eigenvalue rel err vs eigvalsh: {err:.2e}")


if __name__ == "__main__":
    main()
