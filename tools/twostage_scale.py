"""Size the two-stage eigensolver's vectors path at BASELINE scale (VERDICT
r3 #4: "size the vectors-path reflector tensor at n=20,000 on paper and in a
compiled memory_analysis").

Compiles each phase of heev(method="two_stage", want_vectors=True) at growing
n on CPU (compile-only — nothing executes), records the compiled module's
argument/output/temp footprints, fits the n² coefficient, and extrapolates to
n=20,000 f32 against a v5e's 16 GB HBM.  Writes TWOSTAGE_SCALE.md.

Usage: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/twostage_scale.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from force_cpu import force_cpu_backend

force_cpu_backend(virtual_devices=1)

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NB = 128          # stage-1 band width (default_band_nb class)
SIZES = [1024, 2048, 4096]
TARGET_N = 20000


def mem(comp):
    ma = comp.memory_analysis()
    return dict(args=ma.argument_size_in_bytes, out=ma.output_size_in_bytes,
                temp=ma.temp_size_in_bytes)


def compile_phase(fn, *shapes, dtype=jnp.float32):
    args = [jax.ShapeDtypeStruct(s, dtype) for s in shapes]
    return jax.jit(fn).lower(*args).compile()


def main():
    from slate_tpu.linalg.eig import he2hb, hb2st, unmtr_he2hb

    rows = []
    for n in SIZES:
        r = {"n": n}
        # stage 1: dense -> band, returns (band, Vs, Ts)
        c1 = compile_phase(lambda a: he2hb(a, nb=NB), (n, n))
        r["he2hb"] = mem(c1)
        # stage 2 with vectors: band -> tridiag + dense Q2 (pipelined chase)
        c2 = compile_phase(
            lambda b: hb2st(b, kd=NB, want_vectors=True, pipeline=True),
            (n, n))
        r["hb2st_v"] = mem(c2)
        # back-transform: Q1 applied from stacked reflectors to the n x n Z
        nj = -(-n // NB) - 1
        c3 = compile_phase(
            lambda V, T, C: unmtr_he2hb("left", "n", V, T, C),
            (nj, n, NB), (nj, NB, NB), (n, n))
        r["unmtr"] = mem(c3)
        rows.append(r)
        print(r, flush=True)

    # quadratic fit per phase: bytes ~ a*n^2 + b*n + c (temp is the honest
    # "extra memory" number; args/out follow from the shapes analytically)
    def fit_extrapolate(key):
        ns = np.array([r["n"] for r in rows], float)
        ys = np.array([r[key]["temp"] for r in rows], float)
        A = np.stack([ns**2, ns, np.ones_like(ns)], axis=1)
        coef, *_ = np.linalg.lstsq(A, ys, rcond=None)
        return float(coef @ [TARGET_N**2, TARGET_N, 1.0])

    n = TARGET_N
    nj = -(-n // NB) - 1
    f32 = 4
    analytic = {
        "A / band (n^2)": n * n * f32,
        "Vs (nj, n, nb)": nj * n * NB * f32,
        "Ts (nj, nb, nb)": nj * NB * NB * f32,
        "Q2 dense (n^2)": n * n * f32,
        "Z vectors (n^2)": n * n * f32,
    }
    extraps = {k: fit_extrapolate(k) for k in ("he2hb", "hb2st_v", "unmtr")}

    GB = 1 << 30
    with open(os.path.join(REPO, "TWOSTAGE_SCALE.md"), "w") as f:
        f.write("# Two-stage vectors path at n=20,000 (VERDICT r3 #4)\n\n")
        f.write(f"Compiled-module footprints (f32, nb={NB}, CPU backend —\n"
                "memory_analysis of the same XLA program the TPU compiles; "
                "compile-only, nothing executed).\n\n")
        f.write("| n | phase | args | out | temp |\n|---|---|---|---|---|\n")
        for r in rows:
            for ph in ("he2hb", "hb2st_v", "unmtr"):
                m = r[ph]
                f.write(f"| {r['n']} | {ph} | {m['args']/GB:.3f} GB "
                        f"| {m['out']/GB:.3f} GB | {m['temp']/GB:.3f} GB |\n")
        f.write("\n## Analytic tensor sizes at n=20,000 (f32, nb=128)\n\n")
        f.write("| tensor | bytes |\n|---|---|\n")
        total = 0
        for k, v in analytic.items():
            f.write(f"| {k} | {v/GB:.2f} GB |\n")
            total += v
        f.write(f"| **sum (persistent)** | **{total/GB:.2f} GB** |\n")
        f.write("\n## Quadratic-fit temp extrapolation to n=20,000\n\n")
        f.write("| phase | projected temp |\n|---|---|\n")
        for k, v in extraps.items():
            f.write(f"| {k} | {v/GB:.2f} GB |\n")
        peak = max(
            extraps["he2hb"] + analytic["A / band (n^2)"]
            + analytic["Vs (nj, n, nb)"] + analytic["Ts (nj, nb, nb)"],
            extraps["hb2st_v"] + analytic["Q2 dense (n^2)"]
            + analytic["A / band (n^2)"],
            extraps["unmtr"] + analytic["Vs (nj, n, nb)"]
            + analytic["Z vectors (n^2)"] * 2,
        )
        f.write(f"\n**Projected peak phase footprint ≈ {peak/GB:.1f} GB** "
                "(live persistents + phase temp).  A v5e chip has 16 GB HBM: "
                "the n=20,000 vectors path fits on ONE chip only if the peak "
                "stays under ~14 GB after XLA's buffer reuse; otherwise the "
                "distributed stage-1/back-transform path (parallel/eig_dist) "
                "shards Vs and the gemms, and the single-chip residency "
                "drops to the chase's O(n·kd) windows + Q2.\n")
    print("wrote TWOSTAGE_SCALE.md")


if __name__ == "__main__":
    main()
